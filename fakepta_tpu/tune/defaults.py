"""Hand-set dispatch-knob defaults and tuner constants — the ONE place
literal dispatch-knob values may live in library code.

Every other library module takes these knobs as arguments (plumbed from a
caller, a :class:`~fakepta_tpu.tune.store.TunedConfig`, or this module);
the ``hardcoded-dispatch-knob`` analysis rule enforces it
(docs/INVARIANTS.md). Keep this file boring: plain ints and tuples, no
imports beyond the stdlib, so the analyzer, the serve layer and the engine
can all read it without dragging jax in.

The values themselves are the pre-tuner hand-set defaults the repo has
benchmarked since PR 5/9 — they are the "hand-tuned" side of every
``tuned_speedup_x`` A/B (docs/TUNING.md), which is why they must stay
stable rather than chase any one platform.
"""

from __future__ import annotations

# --- engine dispatch knobs (EnsembleSimulator.run) -------------------------

#: default realizations per chunk dispatch (run(chunk=...)'s hand-set value)
DEFAULT_CHUNK = 1024

#: default in-flight chunk depth for the async pipeline (0 = serial loop)
DEFAULT_PIPELINE_DEPTH = 2

#: default statistic path when the constructor picked none ('xla' |
#: 'fused' | 'mega'); the per-path precision default stays with the path
DEFAULT_PATH = "xla"

# --- serve dispatch knobs (fakepta_tpu.serve) ------------------------------

#: default microbatch bucket ladder: geometric with ratio 2, so padding a
#: cohort up to the next bucket wastes < 50% of slots in the worst case and
#: the warm pool compiles O(log(max/min)) executables per lane config
DEFAULT_BUCKETS = (16, 32, 64, 128, 256, 512, 1024)

#: the ladder ratio the bucket model assumes (docs/SERVING.md pad-waste /
#: compile-count tradeoff; mean waste ~ (ratio-1)/(2*ratio) under uniform
#: cohort sizes)
BUCKET_RATIO = 2

#: default bucket ladder for the FLEET load benchmark (docs/SERVING.md
#: "Fleet"): deliberately short — the fleet figure measures routing +
#: aggregate warm capacity across replicas, so the warmup bill is one
#: executable per (spec, bucket) and small-request cohorts cap early
#: instead of exercising ladder breadth (the solo loadgen covers that)
DEFAULT_FLEET_BUCKETS = (16, 32)

# --- fleet lifecycle knobs (fakepta_tpu.serve.health / .autoscale) ---------

#: heartbeat probe period per replica (seconds); the monitor probes every
#: live replica on this cadence while it is healthy
HEARTBEAT_PERIOD_S = 1.0

#: per-probe deadline: a probe that has not answered by now is a MISS —
#: must stay well under the period so misses accumulate quickly
HEARTBEAT_DEADLINE_S = 0.25

#: consecutive probe misses before a replica is SUSPECT (breaker opens:
#: new routes drain away while probing continues with backoff)
HEARTBEAT_SUSPECT_AFTER = 2

#: consecutive probe misses before a suspect replica is WEDGED (still
#: breakered, still probed — a wedged replica can come back)
HEARTBEAT_WEDGED_AFTER = 4

#: consecutive probe successes before the breaker closes again
BREAKER_CLOSE_AFTER = 2

#: suspect-probe exponential backoff: first retry delay and its cap
BREAKER_BACKOFF_BASE_S = 0.5
BREAKER_BACKOFF_CAP_S = 8.0

#: autoscaler: per-replica throughput a healthy fleet should sustain —
#: demand above ``alive * target`` asks for one more replica
AUTOSCALE_TARGET_QPS_PER_REPLICA = 32.0

#: autoscaler hysteresis band (fractional): scale DOWN only when demand
#: sits below ``(1 - band)`` of the post-shrink capacity, so the policy
#: never flaps between two counts on the same steady load
AUTOSCALE_HYSTERESIS = 0.25

#: autoscaler p99 latency trip wires (milliseconds): above the high mark
#: scale up regardless of qps; scale down only below the low mark
AUTOSCALE_P99_HIGH_MS = 2000.0
AUTOSCALE_P99_LOW_MS = 500.0

#: cooldown between scale actions (seconds): one membership change at a
#: time, fully absorbed before the next decision
AUTOSCALE_COOLDOWN_S = 30.0

# --- streaming dispatch knobs (fakepta_tpu.stream) -------------------------

#: append-block bucket ladder: an appended TOA block pads up to the
#: smallest rung >= its width, so every single-epoch append of a P-pulsar
#: array (a handful of TOAs per pulsar) compiles ONE small-block kernel and
#: reuses it forever — the "shape churn never recompiles" contract of
#: docs/STREAMING.md. Same geometric shape as DEFAULT_BUCKETS, smaller
#: rungs (append blocks are epochs, not cohorts).
STREAM_BLOCK_BUCKETS = (8, 16, 32, 64, 128, 256, 512, 1024)

#: growth ratio past the top ladder rung AND for the stream's storage /
#: ECORR-epoch capacity rungs: capacities only ever move to the next
#: power-of-ratio rung, so a stream that doubles its data recompiles
#: O(log growth) times total, not O(appends)
STREAM_GROWTH_RATIO = 2

#: posterior-refresh scheduling (stream/refresh.py RefreshPolicy):
#: refreshing after EVERY append is wasteful — one epoch barely moves the
#: posterior (ROADMAP item 5). A refresh is due after this many appended
#: TOA blocks since the last one...
REFRESH_EVERY_APPENDS = 4

#: ...or earlier, when the rolling detection statistic moved this much in
#: |SNR| since the last refresh (0 disables the SNR trigger; streams
#: without a ``watch`` statistic fall back to the epoch-count trigger)
REFRESH_MIN_SNR_GAIN = 0.5

#: factorized free-spectrum sampling (sample/factorized.py): bins per
#: lane. 1 = fully per-frequency (most lanes, smallest chains); wider
#: blocks amortize per-lane fixed cost when lane count outruns the fleet.
#: The factorization itself is exact for any block width on a regular
#: grid, so this is purely a throughput knob (docs/SAMPLING.md).
FS_LANE_BINS = 4

#: per-frequency incremental refresh (stream/refresh.py
#: FactorizedRefresher): a lane counts as TOUCHED by an append when its
#: data-moment block moved by more than this relative amount
#: (``||dT_new - dT_old||_F / ||dT_old||_F`` over the lane's columns).
#: Untouched lanes keep their posterior — staleness is bounded by this
#: tolerance — so refresh cost is O(bins-touched), not O(bins)
FS_TOUCH_TOL = 1e-3

# --- telemetry-plane knobs (fakepta_tpu.obs.telemetry) ---------------------

#: bounded snapshot ring per replica publisher (and per replica inside the
#: fleet aggregator): at the heartbeat cadence this is minutes of history,
#: and the ring bound is what keeps a scraped-but-never-drained publisher
#: from growing without limit
TELEMETRY_RING_SIZE = 64

#: scrape every Nth successful heartbeat probe (1 = every probe). The
#: scrape RIDES the heartbeat — same mux'd connection, no extra sockets —
#: so this knob is the only telemetry-frequency control
TELEMETRY_SCRAPE_EVERY = 1

#: rollup window (seconds of per-replica snapshot history) used for rates
#: (qps) and the append-latency regression baseline
TELEMETRY_WINDOW_S = 30.0

#: alert thresholds (docs/OBSERVABILITY.md "Alert rules"): p99 request
#: latency over SLO, consecutive heartbeat misses, append-latency
#: regression multiple over the window baseline, and the peak-HBM
#: watermark fraction of the per-device budget
ALERT_P99_SLO_MS = 2000.0
ALERT_HEARTBEAT_MISS_STREAK = 3
ALERT_APPEND_REGRESSION_X = 3.0
ALERT_HBM_WATERMARK_FRAC = 0.9

# --- gateway knobs (fakepta_tpu.gateway) -----------------------------------

#: total in-flight requests the gateway will hold across ALL tenants —
#: the denominator of every tenant's weighted fair share; past it every
#: admission is a per-tenant 429 with a retry hint
GATEWAY_MAX_INFLIGHT = 128

#: default tenant weight when a Tenant does not set one (fair shares are
#: weight / sum(weights) of GATEWAY_MAX_INFLIGHT, floored at one slot)
GATEWAY_DEFAULT_WEIGHT = 1

#: floor for per-tenant retry_after_s hints (the hint scales with the
#: tenant's own recent latency, never below this)
GATEWAY_RETRY_MIN_S = 0.02

#: ...and its cap (a cold tenant with no latency history gets the floor;
#: a backed-up one never waits longer than this before re-probing)
GATEWAY_RETRY_CAP_S = 5.0

#: per-tenant completed-latency ring (the retry-hint / qps window)
GATEWAY_LATENCY_RING = 128

#: LRU bound on the single-flight table: when this many flights are
#: already open, new keys bypass coalescing (dispatch directly, counted
#: ``gateway.coalesce_bypass``) rather than grow the table without bound
GATEWAY_SINGLEFLIGHT_CAP = 512

#: LRU bound on the result store's in-memory payload cache (decoded npz
#: payloads; the on-disk store is the durable plane)
GATEWAY_RESULT_CACHE_CAP = 256

#: bound on on-disk result-store entries: past it ``put`` evicts the
#: oldest entries (index order) and unlinks their payload files
GATEWAY_STORE_CAP = 4096

#: result-store schema tag + version; entries written by a different
#: version are ignored (loud miss-and-recompute, never reinterpreted)
GATEWAY_STORE_SCHEMA = "fakepta_tpu.gateway/1"
GATEWAY_STORE_VERSION = 1

#: environment variable naming the gateway result-store directory; unset
#: falls back to a ``gateway/`` dir beside the tune store
GATEWAY_DIR_ENV = "FAKEPTA_TPU_GATEWAY_DIR"

#: result-store index file name (inside the gateway directory)
GATEWAY_INDEX_FILENAME = "results.json"

#: cutover oracle tolerance: max relative drift between the restaged
#: moments and a fresh restage of the NEW state before the swap aborts
GATEWAY_CUTOVER_RTOL = 1e-10

# --- tuner constants (fakepta_tpu.tune) ------------------------------------

#: store schema tag + version; entries written by a different version are
#: ignored (never silently reinterpreted) and the tuner re-searches
STORE_SCHEMA = "fakepta_tpu.tune/1"
STORE_VERSION = 1

#: environment variable naming the TunedConfig store directory; when unset
#: the store lands beside the persistent compile cache
#: (``FAKEPTA_TPU_COMPILE_CACHE``), and with neither configured it falls
#: back to ``~/.cache/fakepta_tpu/`` so warm starts survive process
#: boundaries by default
TUNE_DIR_ENV = "FAKEPTA_TPU_TUNE_DIR"

#: store file name (inside the tune/compile-cache directory)
STORE_FILENAME = "tuned.json"

#: measured-refinement budget: the search stops issuing probes past this
#: wall-clock spend and keeps the best candidate probed so far (the
#: hand-set default candidate is always probed first, so a budget-expired
#: search still returns a well-defined "no worse than hand-set" choice)
PROBE_BUDGET_S = 120.0

#: per-probe watchdog deadline (a probe that hangs in a drain is aborted
#: and scored as failed instead of killing the search)
PROBE_TIMEOUT_S = 30.0

#: measured chunks per probe (beyond the compile-bearing warm chunk);
#: single digits by design — probes are throughput estimates, not runs
PROBE_CHUNKS = 2

#: pipeline depths the model-first frontier offers the prober (same
#: executable per chunk size, so extra depths cost no recompiles)
DEPTH_CANDIDATES = (0, 2, 4)

#: fraction of per-device HBM the residency model may plan into (headroom
#: for the allocator, collectives and the host's own staging)
HBM_FRACTION = 0.6

#: per-device working-set budget when the backend exposes no memory limit
#: (the CPU stand-in): coarse, deliberately conservative
DEFAULT_BYTES_BUDGET = 2 << 30
