"""CLI: ``python -m fakepta_tpu.tune search|show|apply ...``.

``search`` tunes the dispatch knobs for a synthetic-array spec (the same
declarative surface the serve layer's :class:`~fakepta_tpu.serve
.ArraySpec` speaks), persists the :class:`~fakepta_tpu.tune.TunedConfig`
and optionally writes the obs-diffable ``fakepta_tpu.tune/1`` artifact
(``--out``; gate it with ``python -m fakepta_tpu.obs gate``). ``show``
prints the store. ``apply`` resolves the knobs a tuned run would pick for
the current platform and prints them as one JSON line — the scriptable
form of ``run(tuned=True)``.

Exit 0 on success, 1 when ``apply``/``show`` find nothing resolved, 2 on
usage/configuration errors (mirroring the other subsystem CLIs).
"""

from __future__ import annotations

import argparse
import json
import sys


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="python -m fakepta_tpu.tune",
        description="platform-aware autotuner for the engine dispatch "
                    "surface (docs/TUNING.md)")
    sub = parser.add_subparsers(dest="command", required=True)

    def add_spec_args(p):
        p.add_argument("--npsr", type=int, default=20)
        p.add_argument("--ntoa", type=int, default=156)
        p.add_argument("--n-red", type=int, default=10)
        p.add_argument("--n-dm", type=int, default=10)
        p.add_argument("--gwb-ncomp", type=int, default=10)
        p.add_argument("--data-seed", type=int, default=0)

    search = sub.add_parser(
        "search", help="model-first search + measured probes; persists "
                       "the winning knobs per platform fingerprint")
    add_spec_args(search)
    search.add_argument("--nreal-hint", type=int, default=4096,
                        help="workload scale the knobs will serve (caps "
                             "the chunk ladder)")
    search.add_argument("--budget-s", type=float, default=None,
                        help="probe wall-clock budget (default: "
                             "tune.defaults.PROBE_BUDGET_S)")
    search.add_argument("--probe-chunks", type=int, default=None,
                        help="measured chunks per probe (default: "
                             "tune.defaults.PROBE_CHUNKS)")
    search.add_argument("--max-candidates", type=int, default=12,
                        help="frontier size cap (model-ranked; the "
                             "hand-set default candidate always rides)")
    search.add_argument("--force", action="store_true",
                        help="re-probe even with a warm store entry")
    search.add_argument("--store", default=None,
                        help="store file path (default: "
                             "$FAKEPTA_TPU_TUNE_DIR, else beside the "
                             "persistent compile cache)")
    search.add_argument("--platform", default=None,
                        help="force a jax platform (e.g. cpu)")
    search.add_argument("--out", default=None,
                        help="write the fakepta_tpu.tune/1 artifact here")

    show = sub.add_parser("show", help="print the TunedConfig store")
    show.add_argument("--store", default=None)

    apply_p = sub.add_parser(
        "apply", help="resolve + print the knobs a tuned run would pick "
                      "for the current platform (one JSON line)")
    add_spec_args(apply_p)
    apply_p.add_argument("--store", default=None)
    apply_p.add_argument("--platform", default=None)
    return parser


def _cmd_search(args) -> int:
    from ..serve.spec import ArraySpec
    from .defaults import PROBE_CHUNKS
    from .search import search

    spec = ArraySpec(npsr=args.npsr, ntoa=args.ntoa, n_red=args.n_red,
                     n_dm=args.n_dm, gwb_ncomp=args.gwb_ncomp,
                     data_seed=args.data_seed)
    cfg, info = search(
        spec=spec, nreal_hint=args.nreal_hint, budget_s=args.budget_s,
        probe_chunks=(PROBE_CHUNKS if args.probe_chunks is None
                      else args.probe_chunks),
        max_candidates=args.max_candidates,
        store=args.store, force=args.force, artifact=args.out)
    line = {"tuned": 1, "warm": bool(info["warm"]),
            "tune_probes": int(info["probes"]),
            "tune_probe_s": round(float(info["probe_s"]), 3),
            "family": cfg.family, "knobs": cfg.knobs,
            "metrics": cfg.metrics}
    if info.get("store_path"):
        line["store"] = info["store_path"]
    print(json.dumps(line))
    return 0


def _cmd_show(args) -> int:
    from .store import TuneStore

    store = TuneStore(args.store)
    entries = store.load_entries()
    if store.path is None:
        print("no store configured (set FAKEPTA_TPU_TUNE_DIR, the "
              "persistent compile cache, or pass --store)",
              file=sys.stderr)
        return 1
    print(f"store: {store.path} ({len(entries)} entr"
          f"{'y' if len(entries) == 1 else 'ies'})")
    for key, raw in sorted(entries.items()):
        knobs = raw.get("knobs", {})
        metrics = raw.get("metrics", {})
        fp = raw.get("fingerprint", {})
        print(f"  {key}  platform={fp.get('platform')} "
              f"devices={fp.get('n_devices')} "
              f"knobs={json.dumps(knobs, sort_keys=True)} "
              f"rate={metrics.get('real_per_s_per_chip')}")
    return 0 if entries else 1


def _cmd_apply(args) -> int:
    import jax  # noqa: F401 — fingerprint needs the runtime up

    from ..parallel.mesh import make_mesh
    from ..serve.spec import ArraySpec
    from .search import resolve_for_sim

    spec = ArraySpec(npsr=args.npsr, ntoa=args.ntoa, n_red=args.n_red,
                     n_dm=args.n_dm, gwb_ncomp=args.gwb_ncomp,
                     data_seed=args.data_seed)
    sim = spec.build(mesh=make_mesh())
    cfg = resolve_for_sim(sim, store=args.store)
    if cfg is None:
        print("no tuned entry for this platform x spec family; run "
              "`python -m fakepta_tpu.tune search` first", file=sys.stderr)
        return 1
    print(json.dumps({"family": cfg.family, "knobs": cfg.knobs,
                      "metrics": cfg.metrics, "created": cfg.created}))
    return 0


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if getattr(args, "platform", None):
        import jax
        jax.config.update("jax_platforms", args.platform)
    try:
        if args.command == "search":
            return _cmd_search(args)
        if args.command == "show":
            return _cmd_show(args)
        if args.command == "apply":
            return _cmd_apply(args)
    except (OSError, ValueError) as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 2
    return 2


if __name__ == "__main__":
    sys.exit(main())
