"""``python -m fakepta_tpu.tune`` entry point."""

import sys

from .cli import main

sys.exit(main())
