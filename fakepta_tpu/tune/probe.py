"""Measured refinement: short probes through the existing obs machinery.

A probe is two ordinary :meth:`EnsembleSimulator.run` calls — one
compile-bearing warm chunk, then ``PROBE_CHUNKS`` measured chunks — driven
through the SAME ``run(tuned=...)`` knob override the production warm
start uses, so what the tuner measures is exactly what a tuned run
executes. Everything read back comes from the RunReport the engine already
attaches: the steady-state throughput split, the ``peak_hbm_bytes``
watermark (candidates that blow the residency budget are rejected on
*evidence*, not just the model), and the retrace guard (a candidate that
recompiles in steady state is broken by definition).

Probes degrade instead of killing the search: each runs under a
:class:`~fakepta_tpu.faults.RecoveryPolicy` with a watchdog deadline, and
any exception — OOM, Pallas failure past the degradation ladder, watchdog
abort — scores the candidate as failed with a flight-recorder note
(``tune_probe_failed``) and moves on.
"""

from __future__ import annotations

from typing import Optional

from .. import obs
from ..obs import flightrec
from . import defaults
from .model import Candidate


def run_probe(sim, cand: Candidate, *, seed: int = 2024,
              probe_chunks: int = defaults.PROBE_CHUNKS,
              timeout_s: float = defaults.PROBE_TIMEOUT_S,
              nreal_cap: Optional[int] = None) -> Optional[dict]:
    """Measure one candidate on a prepared simulator; None on failure.

    ``sim`` must already live on the candidate's mesh split (the search
    builds one simulator per ``psr_shards``); path/precision/chunk/depth
    ride the ``tuned=`` knob override. ``nreal_cap`` (the search passes
    ``nreal_hint``) bounds the measured run at the workload scale: a
    chunk equal to the workload runs as ONE chunk there, and measuring
    it as a multi-chunk pipeline would be measuring a shape the workload
    never executes.
    """
    from .. import faults

    knobs = cand.knobs()
    policy = faults.RecoveryPolicy(watchdog_s=timeout_s, backoff_s=0.0,
                                   max_retries=1)
    nreal = max(probe_chunks, 1) * cand.chunk
    if nreal_cap is not None:
        nreal = max(min(nreal, int(nreal_cap)), cand.chunk)
    t0 = obs.now()
    try:
        # warm chunk: bears the trace+compile for this executable shape
        sim.run(cand.chunk, seed=seed, chunk=cand.chunk, tuned=knobs,
                recovery=policy)
        out = sim.run(nreal, seed=seed + 1,
                      chunk=cand.chunk, tuned=knobs, recovery=policy)
    except Exception as exc:   # noqa: BLE001 — a failed candidate is a
        # scored outcome, not a search abort (OOM/hang/ladder-exhausted)
        flightrec.note("tune_probe_failed", knobs=str(knobs),
                       error=repr(exc)[:200])
        return None
    rep = out["report"]
    rep_sum = rep.summary()
    rec = {
        "knobs": knobs,
        "real_per_s_per_chip": float(rep.steady_real_per_s_per_chip()),
        "probe_s": float(obs.now() - t0),
        "retraces": int(rep.retraces),
        "peak_hbm_bytes": int(rep_sum.get("peak_hbm_bytes", 0)),
    }
    flightrec.note("tune_probe", knobs=str(knobs),
                   rate=round(rec["real_per_s_per_chip"], 2),
                   probe_s=round(rec["probe_s"], 3))
    return rec
