"""Trajectory gate: noise-banded regression checks against BENCH history.

``BENCH_r*.json`` is the repo's benchmark trajectory — one row per round.
Until now "did this round regress?" was an eyeball judgement over raw
numbers, which fails in exactly the ways the history shows: CPU stand-in
rounds (r03–r05, dead accelerator tunnel) sit ~200x below the accelerator
round (r02), so any naive diff against "the previous row" either
cries wolf or is silenced entirely. The gate replaces that with a
statistical check:

- history rows are grouped by ``platform`` — and, for scenario golden
  rows, by ``scenario`` — so only **same-platform, same-scenario** rows
  band a new row: a CPU stand-in round can never gate an accelerator
  round, and an ``ng15`` golden row can never band an ``ipta_dr3`` one
  (main-trajectory bench rows carry no ``scenario`` key and keep banding
  against each other exactly as before);
- each metric's noise band is ``k * max(MAD, rel_floor * |median|)`` around
  the per-platform median (MAD — median absolute deviation — is robust to
  the occasional outlier round; the relative floor keeps a zero-MAD
  history from flagging timer noise);
- direction comes from the same tables ``obs compare`` uses
  (:mod:`.report`): throughput down / bytes up / retraces up is a
  regression, run-shape facts are exempt;
- metrics need ``min_history`` same-platform observations before they gate
  at all — a brand-new metric is informational until the history exists.

CLI::

    python -m fakepta_tpu.obs gate new_row.json                 # report only
    python -m fakepta_tpu.obs gate new_row.json --fail-on-regression
    python -m fakepta_tpu.obs gate run.jsonl --history BENCH_r0*.json

The new row may be a bench line (``bench.py`` output), a driver-wrapped
record (``{"parsed": {...}}`` — the committed ``BENCH_r*.json`` shape), or
a RunReport ``.jsonl`` (its summary table is gated). Exit codes mirror
``compare``: 0 clean (or report-only), 1 flagged under
``--fail-on-regression``, 2 usage/IO.
"""

from __future__ import annotations

import glob
import json
import statistics
import warnings
from dataclasses import dataclass
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

from .report import RunReport, metric_exempt, metric_higher_is_better

DEFAULT_HISTORY_GLOB = "BENCH_r*.json"

# bench-row bookkeeping fields that are not metrics at all
_NON_METRIC_KEYS = {"metric", "unit", "platform", "fallback", "nreal_scale",
                    "n", "cmd", "rc", "tail", "scenario"}


def parse_row(text: str) -> Optional[dict]:
    """One bench row from file text: a raw bench line, or the driver-wrapped
    ``{"parsed": row}`` record the committed BENCH_r*.json files use
    (``parsed`` may be null for a crashed round — returns None)."""
    data = json.loads(text)
    if not isinstance(data, dict):
        raise ValueError("bench row must be a JSON object")
    if "parsed" in data and "rc" in data:
        return data["parsed"] if isinstance(data["parsed"], dict) else None
    return data


def load_row(path) -> dict:
    """The row under gate: bench JSON, wrapped record, or RunReport .jsonl
    (whose summary + platform meta becomes the row)."""
    text = Path(path).read_text()
    first = text.lstrip()[:1]
    if first == "{":
        try:
            row = parse_row(text.strip())
        except (ValueError, json.JSONDecodeError):
            row = None
        if row is not None and "kind" not in row:
            return _ensure_platform(row)
    rep = RunReport.load(path)
    row = dict(rep.summary())
    if rep.meta.get("platform") is not None:
        row["platform"] = rep.meta["platform"]
    return _ensure_platform(row)


def _ensure_platform(row: dict) -> dict:
    """Fill a missing ``platform`` from the tuner's platform fingerprint
    (:func:`fakepta_tpu.tune.fingerprint` — the repo's single source of
    platform identity, shared with ``benchmarks/suite.py``'s column).

    A row with no platform used to band against NOTHING (``None`` matches
    no history group) — silently informational forever. Filling it from
    the fingerprint keeps the invariant that matters: stand-in rows can
    still never gate accelerator rows, because the fingerprint of the
    machine running the gate IS the stand-in's platform. Rows that carry
    their platform (every bench row since r06) are returned untouched, so
    gating someone else's row never consults the local runtime.
    """
    if row.get("platform") is not None:
        return row
    try:
        from ..tune import fingerprint
        row = dict(row)
        row["platform"] = fingerprint().platform
    except Exception as exc:   # noqa: BLE001 — recorded, not swallowed
        # no jax runtime here (bare gate CLI on a build box): the row
        # stays platform-less and informational, with the reason kept
        warnings.warn(f"could not fingerprint the platform for a "
                      f"platform-less row: {exc!r}", RuntimeWarning,
                      stacklevel=2)
    return row


def load_history(paths: Sequence, warn=None) -> List[dict]:
    """Parse history rows, dropping unparseable/crashed rounds WITH a
    warning (a round that produced no row cannot band anything, but a
    silently-vanishing history file is how a gate quietly stops gating).

    ``warn`` is a ``callable(str)`` (the CLI prints to stderr); the default
    routes through :mod:`warnings` so library callers see it too.
    """
    if warn is None:
        warn = lambda m: warnings.warn(m, RuntimeWarning, stacklevel=3)  # noqa: E731
    rows: List[dict] = []
    for p in paths:
        try:
            row = parse_row(Path(p).read_text())
        except (OSError, ValueError, json.JSONDecodeError) as exc:
            warn(f"skipping malformed history row {p}: {exc}")
            continue
        if row:
            rows.append(row)
        else:
            warn(f"skipping history row {p}: crashed round "
                 f"(parsed=null) or empty row")
    return rows


@dataclass
class GateResult:
    metric: str
    new: float
    median: float
    band: float
    n_history: int
    verdict: str        # "ok" | "regression" | "improved" | "info"


def _numeric(v) -> Optional[float]:
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return None
    return float(v)


def gate_row(new_row: dict, history: Sequence[dict], k: float = 3.0,
             rel_floor: float = 0.05,
             min_history: int = 2) -> List[GateResult]:
    """Band every gateable metric of ``new_row`` against same-platform,
    same-scenario history; see the module docstring for the banding rule.

    ``scenario`` is part of the grouping identity exactly like
    ``platform``: a row without one (every main-trajectory bench row)
    only sees history rows without one, and a golden-run row only sees
    its own scenario's trajectory — reduced ``ska_10k`` figures can never
    band ``flagship_100`` figures even on the same machine.
    """
    platform = new_row.get("platform")
    scenario = new_row.get("scenario")
    same = [r for r in history if r.get("platform") == platform
            and r.get("scenario") == scenario]
    results: List[GateResult] = []
    for key in sorted(new_row):
        if key in _NON_METRIC_KEYS:
            continue
        new_v = _numeric(new_row[key])
        if new_v is None:
            continue
        obs_vals = [v for r in same
                    if (v := _numeric(r.get(key))) is not None]
        if len(obs_vals) < min_history:
            results.append(GateResult(key, new_v, new_v, 0.0,
                                      len(obs_vals), "info"))
            continue
        med = statistics.median(obs_vals)
        mad = statistics.median([abs(v - med) for v in obs_vals])
        band = k * max(mad, rel_floor * abs(med))
        if metric_exempt(key):
            verdict = "info"
        elif metric_higher_is_better(key):
            verdict = ("regression" if new_v < med - band else
                       "improved" if new_v > med + band else "ok")
        else:
            verdict = ("regression" if new_v > med + band else
                       "improved" if new_v < med - band else "ok")
        results.append(GateResult(key, new_v, med, band,
                                  len(obs_vals), verdict))
    return results


def format_gate(results: Sequence[GateResult], platform,
                n_history: int) -> Tuple[str, List[str]]:
    """Human table + the list of regressed metric names."""
    lines = [f"gating against {n_history} same-platform "
             f"(platform={platform!r}) history row(s)",
             f"{'metric':<32} {'new':>14} {'median':>14} {'band':>12} "
             f"{'n':>3}  verdict"]
    regressions = []
    for r in results:
        mark = {"regression": "  << REGRESSION", "improved": "  (improved)",
                "info": "  (no band: insufficient history)"
                if r.n_history < 2 else "  (informational)"}.get(
                    r.verdict, "")
        lines.append(f"{r.metric:<32} {r.new:>14g} {r.median:>14g} "
                     f"{r.band:>12g} {r.n_history:>3}  {r.verdict}{mark}")
        if r.verdict == "regression":
            regressions.append(r.metric)
    return "\n".join(lines), regressions


def resolve_history(args_history: Optional[Sequence[str]]) -> List[str]:
    """History paths: explicit files/globs, else ./BENCH_r*.json."""
    patterns = list(args_history) if args_history else [DEFAULT_HISTORY_GLOB]
    paths: List[str] = []
    for pat in patterns:
        hits = sorted(glob.glob(pat))
        paths.extend(hits if hits else ([pat] if Path(pat).exists() else []))
    return paths
