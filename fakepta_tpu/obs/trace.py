"""Run-timeline export as Chrome trace-event JSON (Perfetto-viewable).

The async chunk pipeline (docs/PERFORMANCE.md) *claims* overlap — writer
drains behind the next chunk's execute, host precompute hidden under device
work — but until now the only evidence was aggregate ``stall_s`` /
``ckpt_wait_s`` scalars. The engine now records a **timeline**: per-chunk
span records (run-relative ``t0``/``dur`` seconds plus a logical lane
``tid``) taken on both the dispatch thread and the pipeline's writer
thread. This module converts those records — straight from a RunReport
artifact — to the Chrome trace-event JSON format, so the run's concurrency
is a picture instead of a claim:

    python -m fakepta_tpu.obs trace run.jsonl -o trace.json
    # open https://ui.perfetto.dev and load trace.json

Lanes (one track per ``tid``): ``main`` (dispatch loop: per-chunk dispatch
spans, staging/precompute of host-f64 CGW bulks, depth-bound stalls,
donation-recycle instants), ``device`` (execute spans: dispatch to
outputs-materialized — the device-side residency of each chunk), and
``writer`` (drain spans with nested checkpoint appends). The compiled
program's stage names (``obs.span``) are attached as instant markers on the
device lane; per-op device timing still comes from ``obs.trace()`` (the
jax profiler) — this timeline is the *host-side pipeline structure*, which
the profiler does not show.

Multi-process runs write one event-log shard per host
(``run(eventlog=dir)`` → ``events-p<process>.jsonl``); passing all shards
to this exporter merges them into a single trace with one **pid per host**
(``trace shards/*.jsonl -o trace.json`` — run it on process 0 or offline).
Timestamps are per-host run-relative clocks; lanes align at run start,
which is what the per-host overlap question needs.

The emitted JSON follows the Chrome trace-event format ("JSON Object
Format": a top-level ``traceEvents`` list of ``ph: "X"/"i"/"M"`` events
with microsecond ``ts``/``dur``); :func:`validate_trace` checks the
invariants the format requires and the tests pin it.

**Trace propagation** (docs/OBSERVABILITY.md): serve-layer timeline spans
carry the router-minted request ``trace_id`` in their args (cohort spans
carry the coalesced ``trace_ids`` list). :func:`build_trace` links every
span sharing a trace id into one Chrome **flow** (``ph: "s"/"t"/"f"``
events with a shared numeric ``id``), so a request reads as one causal
arrow router → replica → engine across pid lanes — and a failed-over
request's spans on the dead and surviving replicas are joined by the
same flow.
"""

from __future__ import annotations

import json
from typing import Dict, Iterable, List, Optional, Sequence

from .report import RunReport

# stable thread ids per logical lane (sort order = display order);
# lanes the serve layer adds ("serve", "router") are allocated past these
# per report, first-seen order
TID = {"main": 0, "device": 1, "writer": 2}

_VALID_PH = {"X", "i", "M"}
#: flow phases (start/step/finish) — trace-id links across pid lanes
_FLOW_PH = {"s", "t", "f"}


def timeline_events(report: RunReport, pid: Optional[int] = None) -> List[dict]:
    """Chrome trace events for one report's recorded timeline.

    ``pid`` defaults to the report's ``meta.process_index`` (0 when the run
    predates multi-host metadata) — one process lane per host shard.
    """
    meta = report.meta or {}
    if pid is None:
        pid = int(meta.get("process_index", 0))
    events: List[dict] = []

    label = (f"fakepta_tpu run p{pid}"
             f" [{meta.get('statistic_path', '?')}"
             f", depth {meta.get('pipeline_depth', '?')}"
             f", {meta.get('platform', '?')}]")
    events.append({"ph": "M", "pid": pid, "tid": 0, "name": "process_name",
                   "args": {"name": label}})
    events.append({"ph": "M", "pid": pid, "tid": 0, "name": "process_sort_index",
                   "args": {"sort_index": pid}})
    # lane table: the three engine lanes plus any serve-layer lanes this
    # report's timeline introduces ("serve", "router"), in first-seen
    # order — unknown lanes get their own track instead of stacking on
    # the dispatch lane
    lanes: Dict[str, int] = dict(TID)
    for ev in report.timeline:
        lane = str(ev.get("tid", "main"))
        if lane not in lanes:
            lanes[lane] = max(lanes.values()) + 1
    for lane, tid in lanes.items():
        events.append({"ph": "M", "pid": pid, "tid": tid,
                       "name": "thread_name", "args": {"name": lane}})
        events.append({"ph": "M", "pid": pid, "tid": tid,
                       "name": "thread_sort_index",
                       "args": {"sort_index": tid}})

    first_exec_t0 = None
    for ev in report.timeline:
        tid = lanes[str(ev.get("tid", "main"))]
        name = str(ev.get("name", "?"))
        t0 = float(ev.get("t0", 0.0))
        args = {k: v for k, v in ev.items()
                if k not in ("name", "t0", "dur", "tid")}
        if ev.get("dur") is None:
            events.append({"ph": "i", "pid": pid, "tid": tid, "name": name,
                           "ts": t0 * 1e6, "s": "t", "args": args})
            continue
        if name == "execute" and first_exec_t0 is None:
            first_exec_t0 = t0
        events.append({"ph": "X", "pid": pid, "tid": tid, "name": name,
                       "ts": t0 * 1e6, "dur": float(ev["dur"]) * 1e6,
                       "args": args})

    # the compiled program's stage names, as instant markers on the device
    # lane at the first execute span (per-op timing is the jax profiler's
    # job; these mark WHAT the program contains)
    for span in report.spans:
        events.append({"ph": "i", "pid": pid, "tid": TID["device"],
                       "name": f"stage:{span}",
                       "ts": (first_exec_t0 or 0.0) * 1e6, "s": "t",
                       "args": {}})
    return events


def flow_events(events: Sequence[dict]) -> List[dict]:
    """Chrome flow events linking spans that share a request trace id.

    Scans built span events for ``args.trace_id`` (and each entry of a
    cohort span's ``args.trace_ids``), groups by trace id, and for every
    id carried by two or more spans emits an ``s``/``t``.../``f`` chain
    with a shared numeric flow ``id``, each link coincident with its
    anchor span's start (Perfetto binds a flow event to the enclosing
    slice on the same pid/tid). A failed-over request therefore draws one
    arrow through the router span, the dead replica's spans, and the
    surviving replica's spans.
    """
    groups: Dict[str, List[dict]] = {}
    for ev in events:
        if ev.get("ph") != "X":
            continue    # flows bind to slices, not instants/metadata
        args = ev.get("args") or {}
        ids = []
        if args.get("trace_id"):
            ids.append(str(args["trace_id"]))
        ids.extend(str(t) for t in (args.get("trace_ids") or ()))
        for trace_id in ids:
            groups.setdefault(trace_id, []).append(ev)
    flows: List[dict] = []
    flow_id = 0
    for trace_id in sorted(groups):
        chain = sorted(groups[trace_id],
                       key=lambda e: (e["ts"], e["pid"], e["tid"]))
        if len(chain) < 2:
            continue    # nothing to link
        flow_id += 1
        last = len(chain) - 1
        for k, anchor in enumerate(chain):
            link = {"ph": "s" if k == 0 else "f" if k == last else "t",
                    "cat": "trace", "name": f"trace:{trace_id}",
                    "id": flow_id, "pid": anchor["pid"],
                    "tid": anchor["tid"], "ts": anchor["ts"]}
            if k == last:
                link["bp"] = "e"    # bind to the enclosing slice
            flows.append(link)
    return flows


def build_trace(reports: Sequence[RunReport]) -> dict:
    """One Chrome trace object merging the given reports (pid per shard).

    Shards sharing a ``process_index`` (or lacking one) are assigned
    distinct pids in input order, so merging N single-host artifacts never
    silently stacks their lanes. Spans sharing a request ``trace_id``
    across shards are joined by flow events (:func:`flow_events`).
    """
    events: List[dict] = []
    used_pids: set = set()
    for i, rep in enumerate(reports):
        pid = int((rep.meta or {}).get("process_index", i))
        while pid in used_pids:
            pid += 1
        used_pids.add(pid)
        events.extend(timeline_events(rep, pid=pid))
    flows = flow_events(events)
    events.extend(flows)
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "metadata": {"tool": "fakepta_tpu.obs trace",
                     "shards": len(reports), "flows": len(flows)},
    }


def validate_trace(trace: dict) -> None:
    """Raise ValueError unless ``trace`` is valid Chrome trace-event JSON.

    Checks the format's structural invariants: a ``traceEvents`` list whose
    entries carry a known ``ph``, integer ``pid``/``tid``, numeric
    non-negative ``ts`` (and ``dur`` for complete events), string names,
    and JSON-serializable ``args``. Duration events must not claim negative
    time. This is what the tier-1 schema test pins.
    """
    if not isinstance(trace, dict) or not isinstance(
            trace.get("traceEvents"), list):
        raise ValueError("trace must be an object with a traceEvents list")
    for i, ev in enumerate(trace["traceEvents"]):
        where = f"traceEvents[{i}]"
        if not isinstance(ev, dict):
            raise ValueError(f"{where}: not an object")
        ph = ev.get("ph")
        if ph not in _VALID_PH and ph not in _FLOW_PH:
            raise ValueError(f"{where}: unknown ph {ph!r}")
        if not isinstance(ev.get("name"), str) or not ev["name"]:
            raise ValueError(f"{where}: missing name")
        for key in ("pid", "tid"):
            if not isinstance(ev.get(key), int):
                raise ValueError(f"{where}: {key} must be an int")
        if ph == "M":
            if not isinstance(ev.get("args"), dict):
                raise ValueError(f"{where}: metadata event without args")
            continue
        ts = ev.get("ts")
        if not isinstance(ts, (int, float)) or ts < 0:
            raise ValueError(f"{where}: ts must be a non-negative number")
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                raise ValueError(f"{where}: complete event needs dur >= 0")
        if ph in _FLOW_PH and not isinstance(ev.get("id"), (int, str)):
            raise ValueError(f"{where}: flow event needs an id")
    json.dumps(trace)   # everything must serialize


def load_reports(paths: Iterable) -> List[RunReport]:
    """Load report/event-log shards (any file RunReport.save wrote)."""
    return [RunReport.load(p) for p in paths]


def export(paths: Sequence, out_path) -> dict:
    """Load shards, build + validate the merged trace, write it; returns
    summary counts for the CLI."""
    reports = load_reports(paths)
    trace = build_trace(reports)
    validate_trace(trace)
    with open(out_path, "w") as fh:
        json.dump(trace, fh)
    spans = sum(1 for ev in trace["traceEvents"] if ev["ph"] == "X")
    pids = {ev["pid"] for ev in trace["traceEvents"]}
    return {"events": len(trace["traceEvents"]), "spans": spans,
            "processes": len(pids),
            "flows": int(trace["metadata"].get("flows", 0)),
            "path": str(out_path)}


def overlap_s(report: RunReport, a: str = "drain", b: str = "execute") -> float:
    """Total seconds where any ``a`` span overlaps any ``b`` span of a
    LATER chunk — the pipeline's measured concurrency (used by the tests'
    acceptance and handy interactively)."""
    spans_a = [ev for ev in report.timeline if ev.get("name") == a
               and ev.get("dur") is not None]
    spans_b = [ev for ev in report.timeline if ev.get("name") == b
               and ev.get("dur") is not None]
    total = 0.0
    for ea in spans_a:
        for eb in spans_b:
            if eb.get("chunk", -1) <= ea.get("chunk", -1):
                continue
            lo = max(float(ea["t0"]), float(eb["t0"]))
            hi = min(float(ea["t0"]) + float(ea["dur"]),
                     float(eb["t0"]) + float(eb["dur"]))
            total += max(hi - lo, 0.0)
    return total
