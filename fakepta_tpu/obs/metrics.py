"""Metrics core: counters, gauges, timing histograms, and a JSON-lines sink.

The structured half of the observability layer (docs/OBSERVABILITY.md). A
:class:`Collector` owns the run's metrics; the engine (and any other producer)
reports through the module-level helpers — ``count``/``gauge``/``observe``/
``record_span``/``event`` — which write to the *active* collector and are
no-ops when none is installed. That no-op path is the zero-overhead-by-default
contract: with no collector, instrumentation costs one truthiness check on the
host, and nothing at all inside compiled programs (span/trace hooks execute
only at trace time).

Event schema (one JSON object per line, ``SCHEMA`` below versions it):

    {"kind": "header",  "schema": ..., "meta": {...}}        # first line
    {"kind": "span",    "name": "white"}
    {"kind": "counter", "name": "chunks", "value": 2}
    {"kind": "gauge",   "name": "cost.bytes_per_chunk", "value": 1.07e8}
    {"kind": "timing",  "name": "chunk_wall_s", "values": [..]}
    {"kind": "event",   "name": ..., "value": ..., "attrs": {...}}
    {"kind": "summary", "metrics": {...}}                    # last line

``subscribe_jax_monitoring()`` bridges ``jax.monitoring`` (compilation /
tracing duration events, where the running jax exposes them) into the active
collector, so compile time is a recorded artifact instead of a stopwatch
guess.
"""

from __future__ import annotations

import contextlib
import json
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional

from . import flightrec

SCHEMA = "fakepta_tpu.obs/1"

#: schema era for logs carrying the telemetry-plane record kinds
#: (``telemetry`` snapshot lines and ``alert`` lines — docs/OBSERVABILITY.md
#: "Telemetry plane"). Writers that emit those kinds stamp this schema;
#: readers accept both eras because /2 is a strict superset of /1 (every /1
#: kind parses unchanged). Anything else still fails loudly.
SCHEMA_V2 = "fakepta_tpu.obs/2"

ACCEPTED_SCHEMAS = (SCHEMA, SCHEMA_V2)

#: regex every library-emitted metric name must match (lowercase dotted
#: words) — the ``metric-name-discipline`` analysis rule enforces it.
METRIC_NAME_RE = r"^[a-z][a-z0-9_.]*$"

#: Declared metric-name registry. Library calls to ``count``/``gauge``/
#: ``observe`` must pass a literal name from this table (audited by the
#: ``metric-name-discipline`` analysis rule, docs/INVARIANTS.md), so the
#: Prometheus exposition derived from collector state keeps stable names:
#: renaming a metric is a schema change made HERE, not a drive-by edit at a
#: call site.
METRIC_NAMES = frozenset({
    # fleet lifecycle (serve/health.py, serve/fleet.py, serve/autoscale.py)
    "fleet.scale_events", "fleet.heartbeat_misses", "fleet.breaker_opens",
    "fleet.joins", "fleet.drains",
    # serving plane (serve/scheduler.py)
    "serve.stream_requests",
    # streaming ingestion (stream/state.py, stream/refresh.py,
    # detect/streaming.py)
    "stream.detections", "stream.promotions", "stream.refreshes",
    "stream.refresh_skips", "stream.recompiles", "stream.compiles",
    "stream.rebuckets", "stream.appends", "stream.replays",
    # factorized free-spectrum lanes (sample/factorized.py,
    # stream/refresh.py FactorizedRefresher)
    "sample.lane_runs", "stream.fs_refreshes", "stream.fs_lanes_refreshed",
    "stream.fs_bins_touched",
    # retrace guard (parallel/montecarlo.py, sample/run.py)
    "obs.traces", "obs.retraces",
    # engine chunk accounting + async-pipeline overlap counters
    # (parallel/montecarlo.py, sample/run.py)
    "obs.chunks", "pipeline.d2h_async", "pipeline.h2d_prefetch",
    # recovery ladder (stream/state.py, parallel/montecarlo.py,
    # faults/plan.py)
    "faults.rollbacks", "faults.injected", "faults.degradations",
    "faults.retries",
    # HBM watermark live gauge (obs/memwatch.py)
    "obs.peak_hbm_bytes",
    # jax.monitoring bridge (renamed duration events, emitted internally)
    "jax.backend_compile_s", "jax.trace_s", "jax.lowering_s",
    # telemetry plane (obs/telemetry.py, serve/streams.py, stream/refresh.py,
    # sample/run.py)
    "telemetry.scrapes", "telemetry.scrape_errors", "telemetry.alerts",
    "serve.append_latency_s", "stream.refresh_gate_opens",
    "stream.refresh_gate_holds", "sample.segments_done",
    # gateway tier (gateway/core.py, gateway/store.py, gateway/cutover.py)
    "gateway.requests", "gateway.hits", "gateway.coalesced",
    "gateway.throttles", "gateway.auth_failures", "gateway.cache_rejects",
    "gateway.store_puts", "gateway.store_evictions",
    "gateway.coalesce_bypass", "gateway.cutovers", "gateway.cutover_aborts",
})

# jax.monitoring duration events forwarded into collectors, renamed to stable
# schema keys (the raw jax event paths are an implementation detail of the
# running jax version)
_JAX_DURATION_EVENTS = {
    "/jax/core/compile/backend_compile_duration": "jax.backend_compile_s",
    "/jax/core/compile/jaxpr_trace_duration": "jax.trace_s",
    "/jax/core/compile/jaxpr_to_mlir_module_duration": "jax.lowering_s",
}


@dataclass
class Collector:
    """One run's worth of metrics: counters, gauges, timings, spans, events."""

    counters: Dict[str, float] = field(default_factory=dict)
    gauges: Dict[str, float] = field(default_factory=dict)
    timings: Dict[str, List[float]] = field(default_factory=dict)
    spans: List[str] = field(default_factory=list)
    events: List[dict] = field(default_factory=list)

    def count(self, name: str, n: float = 1) -> None:
        self.counters[name] = self.counters.get(name, 0) + n

    def gauge(self, name: str, value: float) -> None:
        self.gauges[name] = float(value)

    def observe(self, name: str, seconds: float) -> None:
        self.timings.setdefault(name, []).append(float(seconds))

    def record_span(self, name: str) -> None:
        if name not in self.spans:
            self.spans.append(name)

    def event(self, name: str, value: Any = None, **attrs) -> None:
        ev = {"name": name}
        if value is not None:
            ev["value"] = value
        if attrs:
            ev["attrs"] = attrs
        self.events.append(ev)

    def timing_summary(self) -> Dict[str, dict]:
        return {name: {"n": len(ts), "total_s": sum(ts),
                       "mean_s": sum(ts) / len(ts)}
                for name, ts in self.timings.items() if ts}


# Active-collector stack. Thread-local so concurrent runs (e.g. two
# simulators driven from different host threads) do not interleave metrics.
_state = threading.local()


def active() -> Optional[Collector]:
    """The innermost installed collector, or None (the zero-overhead case)."""
    stack = getattr(_state, "stack", None)
    return stack[-1] if stack else None


@contextlib.contextmanager
def collect(collector: Optional[Collector] = None) -> Iterator[Collector]:
    """Install ``collector`` as the active sink for the ``with`` body."""
    if collector is None:
        collector = Collector()
    stack = getattr(_state, "stack", None)
    if stack is None:
        stack = _state.stack = []
    stack.append(collector)
    try:
        yield collector
    finally:
        stack.pop()


def count(name: str, n: float = 1) -> None:
    c = active()
    if c is not None:
        c.count(name, n)


def gauge(name: str, value: float) -> None:
    c = active()
    if c is not None:
        c.gauge(name, value)


def observe(name: str, seconds: float) -> None:
    c = active()
    if c is not None:
        c.observe(name, seconds)


def record_span(name: str) -> None:
    c = active()
    if c is not None:
        c.record_span(name)


def event(name: str, value: Any = None, **attrs) -> None:
    # events always land in the crash flight recorder's bounded ring
    # (obs.flightrec) — one deque append, collector or not — so a killed
    # run's dump contains the tail of whatever the engine reported
    flightrec.note(name, **({"value": value, **attrs} if value is not None
                            else attrs))
    c = active()
    if c is not None:
        c.event(name, value, **attrs)


_monitoring_subscribed = False


def subscribe_jax_monitoring() -> bool:
    """Bridge ``jax.monitoring`` duration events into the active collector.

    Idempotent (listeners register once per process) and safe on jax builds
    without the monitoring module. The listener itself is a no-op when no
    collector is active, so subscription adds no steady-state cost. Returns
    whether the bridge is installed.
    """
    global _monitoring_subscribed
    if _monitoring_subscribed:
        return True
    try:
        from jax import monitoring
    except ImportError:                                  # pragma: no cover
        return False
    if not hasattr(monitoring, "register_event_duration_secs_listener"):
        return False                                     # pragma: no cover

    def _on_duration(jax_event: str, duration: float, **attrs) -> None:
        name = _JAX_DURATION_EVENTS.get(jax_event)
        if name is None:
            return
        c = active()
        if c is not None:
            c.observe(name, duration)

    monitoring.register_event_duration_secs_listener(_on_duration)
    _monitoring_subscribed = True
    return True


class EventLog:
    """Append-only JSON-lines sink with the stable ``SCHEMA`` framing.

    The write path: ``append`` dicts, ``save`` to a ``.jsonl`` file (header
    first, summary last). The read path: ``EventLog.load`` round-trips any
    file this module (or :meth:`RunReport.save <.report.RunReport.save>`)
    wrote. Schema mismatches fail loudly — a silent cross-version diff is
    exactly the "mixing three eras of numbers" failure this layer exists to
    end.
    """

    def __init__(self, meta: Optional[dict] = None, schema: str = SCHEMA):
        if schema not in ACCEPTED_SCHEMAS:
            raise ValueError(f"unknown event-log schema {schema!r}; "
                             f"accepted: {ACCEPTED_SCHEMAS}")
        self.meta = dict(meta or {})
        self.schema = schema
        self.lines: List[dict] = []

    def append(self, kind: str, **fields) -> dict:
        ev = {"kind": kind, **fields}
        self.lines.append(ev)
        return ev

    def extend_from(self, collector: Collector) -> None:
        """Serialize a collector's state into schema lines."""
        for name in collector.spans:
            self.append("span", name=name)
        for name, value in sorted(collector.counters.items()):
            self.append("counter", name=name, value=value)
        for name, value in sorted(collector.gauges.items()):
            self.append("gauge", name=name, value=value)
        for name, values in sorted(collector.timings.items()):
            self.append("timing", name=name, values=list(values))
        for ev in collector.events:
            self.append("event", **ev)

    def to_jsonl(self, summary: Optional[dict] = None) -> str:
        out = [json.dumps({"kind": "header", "schema": self.schema,
                           "meta": self.meta})]
        out += [json.dumps(line) for line in self.lines]
        if summary is not None:
            out.append(json.dumps({"kind": "summary", "metrics": summary}))
        return "\n".join(out) + "\n"

    def save(self, path, summary: Optional[dict] = None) -> str:
        with open(path, "w") as fh:
            fh.write(self.to_jsonl(summary))
        return str(path)

    @classmethod
    def parse(cls, text: str) -> "EventLog":
        log = cls()
        for i, raw in enumerate(text.splitlines()):
            raw = raw.strip()
            if not raw:
                continue
            line = json.loads(raw)
            if i == 0:
                if line.get("kind") != "header":
                    raise ValueError("event log must start with a header line")
                if line.get("schema") not in ACCEPTED_SCHEMAS:
                    raise ValueError(
                        f"event-log schema {line.get('schema')!r} not in "
                        f"{ACCEPTED_SCHEMAS}: refusing to mix telemetry eras")
                log.meta = line.get("meta", {})
                log.schema = line["schema"]
                continue
            log.lines.append(line)
        return log

    @classmethod
    def load(cls, path) -> "EventLog":
        with open(path) as fh:
            return cls.parse(fh.read())

    def summary(self) -> Optional[dict]:
        for line in reversed(self.lines):
            if line.get("kind") == "summary":
                return line.get("metrics", {})
        return None
