"""Prometheus text-format exposition of a telemetry rollup.

The ``metrics`` protocol kind (``serve/cli.py``) and
``ServeFleet.metrics_text()`` render through here. Metric names are a
DECLARED schema (the table below, documented in docs/OBSERVABILITY.md
"Prometheus metric names") — scrape configs and dashboards depend on them,
so renaming one is a schema change made here, never inline. Everything is
stdlib string formatting: no client library, version 0.0.4 text format
(``text/plain``), which every Prometheus-compatible scraper accepts.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

#: the exposition schema: metric name -> (type, help). One row per exported
#: family; ``render`` refuses names outside this table so the exposition
#: can never drift from the documented schema.
PROM_METRICS: Dict[str, Tuple[str, str]] = {
    "fakepta_up":
        ("gauge", "1 when the replica's health ladder says healthy"),
    "fakepta_serve_qps":
        ("gauge", "windowed completed requests/s per replica"),
    "fakepta_serve_p50_ms":
        ("gauge", "request latency p50 (milliseconds)"),
    "fakepta_serve_p99_ms":
        ("gauge", "request latency p99 (milliseconds)"),
    "fakepta_serve_queue_depth":
        ("gauge", "pending requests in the scheduler queue"),
    "fakepta_serve_requests_total":
        ("counter", "requests admitted since replica start"),
    "fakepta_serve_failed_total":
        ("counter", "requests failed since replica start"),
    "fakepta_pool_warm_entries":
        ("gauge", "resident warm-pool spec entries"),
    "fakepta_pool_warm_max":
        ("gauge", "warm-pool LRU capacity"),
    "fakepta_pool_cache_hit_rate":
        ("gauge", "fraction of dispatches served without a pool build"),
    "fakepta_heartbeat_misses":
        ("gauge", "consecutive heartbeat probe misses"),
    "fakepta_breaker_open":
        ("gauge", "1 when the replica's routing breaker is open"),
    "fakepta_peak_hbm_bytes":
        ("gauge", "peak device-memory watermark (bytes)"),
    "fakepta_stream_appends_total":
        ("counter", "TOA blocks appended to the stream"),
    "fakepta_stream_append_mean_ms":
        ("gauge", "mean stream append latency (milliseconds)"),
    "fakepta_spec_warm_buckets":
        ("gauge", "prewarmed (lane, bucket) executables for the spec"),
    "fakepta_live_gauge":
        ("gauge", "process live gauges (sampler segment progress, "
                  "refresh-gate decisions, ...) keyed by name"),
    "fakepta_fleet_replicas":
        ("gauge", "live replicas in the aggregator window"),
    "fakepta_fleet_qps":
        ("gauge", "fleet-wide windowed requests/s"),
    "fakepta_fleet_queue_depth":
        ("gauge", "fleet-wide pending requests"),
    "fakepta_fleet_p99_ms_max":
        ("gauge", "worst per-replica p99 (milliseconds)"),
    "fakepta_alert_active":
        ("gauge", "1 per currently-firing alert rule"),
    "fakepta_gateway_tenant_qps":
        ("gauge", "windowed completed requests/s per tenant"),
    "fakepta_gateway_tenant_requests_total":
        ("counter", "requests admitted to the gateway per tenant"),
    "fakepta_gateway_tenant_throttles_total":
        ("counter", "429s (quota/fair-share rejections) per tenant"),
    "fakepta_gateway_tenant_hit_rate":
        ("gauge", "fraction of a tenant's requests served from the "
                  "result store"),
    "fakepta_gateway_tenant_queue_share":
        ("gauge", "a tenant's share of the gateway's in-flight slots"),
    "fakepta_gateway_cache_hits_total":
        ("counter", "requests served from the content-addressed store"),
    "fakepta_gateway_cache_rejects_total":
        ("counter", "store entries refused on integrity grounds "
                    "(CRC/schema/fingerprint mismatch)"),
    "fakepta_gateway_coalesced_total":
        ("counter", "requests folded into an in-flight identical leader"),
    "fakepta_gateway_device_seconds_saved":
        ("gauge", "device-seconds not spent thanks to cache hits"),
    "fakepta_gateway_cutovers_total":
        ("counter", "frozen-grid migration cutovers completed"),
}


def _escape(value: str) -> str:
    return (str(value).replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


def _sample(out: List[str], name: str, labels: Dict[str, str],
            value) -> None:
    if name not in PROM_METRICS:
        raise ValueError(f"metric {name!r} is not in the declared "
                         f"PROM_METRICS schema (docs/OBSERVABILITY.md)")
    if labels:
        lab = ",".join(f'{k}="{_escape(v)}"'
                       for k, v in sorted(labels.items()))
        out.append(f"{name}{{{lab}}} {float(value):g}")
    else:
        out.append(f"{name} {float(value):g}")


def render(rollup: dict) -> str:
    """Render an aggregator rollup as Prometheus text exposition."""
    samples: List[str] = []
    used: List[str] = []

    def emit(name, labels, value):
        if name not in used:
            used.append(name)
        _sample(samples, name, labels, value)

    fleet = rollup.get("fleet", {})
    emit("fakepta_fleet_replicas", {}, fleet.get("replicas", 0))
    emit("fakepta_fleet_qps", {}, fleet.get("qps", 0.0))
    emit("fakepta_fleet_queue_depth", {}, fleet.get("queue_depth", 0))
    emit("fakepta_fleet_p99_ms_max", {}, fleet.get("p99_ms_max", 0.0))

    for rid, row in sorted(rollup.get("per_replica", {}).items()):
        lab = {"replica": rid}
        emit("fakepta_up", lab,
             1.0 if row.get("health") == "healthy" else 0.0)
        emit("fakepta_serve_qps", lab, row.get("qps", 0.0))
        emit("fakepta_serve_p50_ms", lab, row.get("p50_ms", 0.0))
        emit("fakepta_serve_p99_ms", lab, row.get("p99_ms", 0.0))
        emit("fakepta_serve_queue_depth", lab, row.get("queue_depth", 0))
        emit("fakepta_serve_requests_total", lab, row.get("requests", 0))
        emit("fakepta_serve_failed_total", lab, row.get("failed", 0))
        emit("fakepta_heartbeat_misses", lab,
             row.get("heartbeat_misses", 0))
        emit("fakepta_breaker_open", lab,
             1.0 if row.get("breaker_open") else 0.0)
        if "warm_entries" in row:
            emit("fakepta_pool_warm_entries", lab, row["warm_entries"])
            emit("fakepta_pool_warm_max", lab, row.get("warm_max", 0))
            emit("fakepta_pool_cache_hit_rate", lab,
                 row.get("cache_hit_rate", 0.0))
        if "peak_hbm_bytes" in row:
            emit("fakepta_peak_hbm_bytes", lab, row["peak_hbm_bytes"])
        for spec, info in sorted(row.get("specs", {}).items()):
            emit("fakepta_spec_warm_buckets", dict(lab, spec=spec),
                 info.get("warm_buckets", 0))
        for stream, info in sorted(row.get("streams", {}).items()):
            slab = dict(lab, stream=stream)
            emit("fakepta_stream_appends_total", slab,
                 info.get("appends", 0))
            if info.get("append_mean_ms") is not None:
                emit("fakepta_stream_append_mean_ms", slab,
                     info["append_mean_ms"])
        for name, value in sorted(row.get("live", {}).items()):
            if isinstance(value, (int, float)) and not isinstance(
                    value, bool):
                emit("fakepta_live_gauge", dict(lab, name=name), value)

    gw = rollup.get("gateway")
    if gw:
        emit("fakepta_gateway_cache_hits_total", {}, gw.get("hits", 0))
        emit("fakepta_gateway_cache_rejects_total", {},
             gw.get("cache_rejects", 0))
        emit("fakepta_gateway_coalesced_total", {}, gw.get("coalesced", 0))
        emit("fakepta_gateway_device_seconds_saved", {},
             gw.get("device_s_saved", 0.0))
        emit("fakepta_gateway_cutovers_total", {}, gw.get("cutovers", 0))
    for tid, row in sorted(rollup.get("tenants", {}).items()):
        lab = {"tenant": tid}
        emit("fakepta_gateway_tenant_qps", lab, row.get("qps", 0.0))
        emit("fakepta_gateway_tenant_requests_total", lab,
             row.get("requests", 0))
        emit("fakepta_gateway_tenant_throttles_total", lab,
             row.get("throttles", 0))
        emit("fakepta_gateway_tenant_hit_rate", lab,
             row.get("hit_rate", 0.0))
        emit("fakepta_gateway_tenant_queue_share", lab,
             row.get("queue_share", 0.0))

    for alert in rollup.get("alerts", []):
        emit("fakepta_alert_active",
             {"rule": alert.get("rule", ""),
              "replica": alert.get("replica", "")}, 1.0)

    out: List[str] = []
    for name in used:
        mtype, help_ = PROM_METRICS[name]
        out.append(f"# HELP {name} {help_}")
        out.append(f"# TYPE {name} {mtype}")
        out.extend(s for s in samples
                   if s.split("{", 1)[0].split(" ", 1)[0] == name)
    return "\n".join(out) + ("\n" if out else "")
