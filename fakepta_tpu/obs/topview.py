"""``obs top``: the fleet telemetry rollup as a refreshing terminal table.

Pure string rendering over a :func:`TelemetryAggregator.rollup
<fakepta_tpu.obs.telemetry.TelemetryAggregator.rollup>` dict — the CLI
(``obs/cli.py``) supplies the fetch (a live ``telemetry``-kind poll over
the serve socket, or a saved ``fakepta_tpu.obs/2`` log) and the refresh
loop lives here so tests can drive it with a scripted fetch and zero
sleeps.
"""

from __future__ import annotations

import sys
import time
from typing import Callable, List, Optional

_COLUMNS = ("REPLICA", "HEALTH", "QPS", "P50ms", "P99ms", "QUEUE",
            "WARM", "HIT%", "BRKR", "MISS")


def _fmt(value, width: int) -> str:
    if isinstance(value, float):
        text = f"{value:.1f}"
    else:
        text = str(value)
    return text[:width].rjust(width)


def render_table(rollup: dict) -> str:
    """One frame: fleet header, per-replica rows, rollup detail lines."""
    fleet = rollup.get("fleet", {})
    lines: List[str] = []
    lines.append(
        f"fleet: {fleet.get('replicas', 0)} replicas  "
        f"qps={fleet.get('qps', 0.0):.1f}  "
        f"queue={fleet.get('queue_depth', 0)}  "
        f"p99max={fleet.get('p99_ms_max', 0.0):.1f}ms  "
        f"scrapes={fleet.get('ingested', 0)} "
        f"(stale={fleet.get('dropped_stale', 0)})")
    widths = (10, 8, 8, 8, 8, 6, 6, 6, 5, 5)
    lines.append("  ".join(c.rjust(w) for c, w in zip(_COLUMNS, widths)))
    for rid, row in sorted(rollup.get("per_replica", {}).items()):
        warm = (f"{row.get('warm_entries', 0)}/{row.get('warm_max', 0)}"
                if "warm_entries" in row else "-")
        cells = (
            rid, row.get("health", "?"), row.get("qps", 0.0),
            row.get("p50_ms", 0.0), row.get("p99_ms", 0.0),
            row.get("queue_depth", 0), warm,
            f"{100.0 * row.get('cache_hit_rate', 0.0):.0f}"
            if "cache_hit_rate" in row else "-",
            "open" if row.get("breaker_open") else "-",
            row.get("heartbeat_misses", 0))
        lines.append("  ".join(_fmt(c, w) for c, w in zip(cells, widths)))
        for spec, info in sorted(row.get("specs", {}).items()):
            lines.append(f"    spec {spec[:12]}: "
                         f"warm_buckets={info.get('warm_buckets', 0)}")
        for stream, info in sorted(row.get("streams", {}).items()):
            mean = info.get("append_mean_ms")
            lines.append(
                f"    stream {stream}: appends={info.get('appends', 0)}"
                + (f" mean={mean:.2f}ms" if mean is not None else ""))
        gates = {k: v for k, v in row.get("live", {}).items()
                 if k.startswith(("stream.refresh", "sample."))}
        for name, value in sorted(gates.items()):
            lines.append(f"    {name} = {value}")
    gw = rollup.get("gateway")
    if gw:
        lines.append(
            f"gateway: requests={gw.get('requests', 0)}  "
            f"hits={gw.get('hits', 0)} "
            f"({100.0 * gw.get('hit_rate', 0.0):.0f}%)  "
            f"coalesced={gw.get('coalesced', 0)}  "
            f"throttles={gw.get('throttles', 0)}  "
            f"saved={gw.get('device_s_saved', 0.0):.2f}s")
    tenants = rollup.get("tenants", {})
    if tenants:
        twidths = (10, 8, 8, 6, 6, 6, 8)
        lines.append("  ".join(c.rjust(w) for c, w in zip(
            ("TENANT", "QPS", "REQS", "429s", "HIT%", "SHARE", "P99ms"),
            twidths)))
        for tid, row in sorted(tenants.items()):
            cells = (
                tid, row.get("qps", 0.0), row.get("requests", 0),
                row.get("throttles", 0),
                f"{100.0 * row.get('hit_rate', 0.0):.0f}",
                f"{100.0 * row.get('queue_share', 0.0):.0f}%",
                row.get("p99_ms", 0.0))
            lines.append("  ".join(_fmt(c, w)
                                   for c, w in zip(cells, twidths)))
    for rid in sorted(rollup.get("retired", {})):
        lines.append(f"  retired: {rid}")
    for alert in rollup.get("alerts", []):
        lines.append(f"  ALERT {alert.get('rule')} on "
                     f"{alert.get('replica')}: "
                     + ", ".join(f"{k}={v}" for k, v in sorted(
                         alert.items()) if k not in ("rule", "replica")))
    return "\n".join(lines) + "\n"


def run_top(fetch: Callable[[], dict], interval_s: float = 1.0,
            iterations: Optional[int] = None, out=None) -> int:
    """The refresh loop: fetch → render → clear-and-redraw.

    ``iterations=None`` runs until the fetch raises KeyboardInterrupt /
    EOFError (the live terminal case); tests pass a finite count and a
    StringIO ``out``. Returns the number of frames rendered.
    """
    out = out if out is not None else sys.stdout
    frames = 0
    while iterations is None or frames < iterations:
        try:
            rollup = fetch()
        except (KeyboardInterrupt, EOFError):
            break
        if frames and out.isatty():           # pragma: no cover - terminal
            out.write("\x1b[2J\x1b[H")
        out.write(render_table(rollup))
        out.flush()
        frames += 1
        if iterations is not None and frames >= iterations:
            break
        time.sleep(interval_s)
    return frames
