"""Trace spans and device-synced timing (absorbs ``utils/profiling.py``).

``span(name)`` is the engine's stage annotation: inside a traced program it
names the emitted ops (``jax.named_scope``, so the stage shows up attributed
in a Perfetto/TensorBoard device trace) and marks the host timeline
(``jax.profiler.TraceAnnotation``); it also records the span name to the
active :mod:`~fakepta_tpu.obs.metrics` collector. All of that happens at
*trace time only* — a cached jitted call never re-enters the context manager,
so steady-state chunks pay nothing (the host-sync-in-jit invariant,
docs/INVARIANTS.md).

``Timer`` keeps the device-sync semantics of the old ``utils.profiling.Timer``
— ``block_until_ready`` on whatever the block hands to ``set_result``, so the
recorded time covers device execution, not just async dispatch — and fixes
its exception bug: the elapsed time is now recorded in a ``finally``, so a
raising block still leaves a measurement (previously the section vanished,
which is how failed runs ended up with no timing evidence at all).
"""

from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass, field
from typing import Dict, List

import jax

from . import metrics

annotation = jax.profiler.TraceAnnotation    # named spans inside a trace


def now() -> float:
    """The library's sanctioned monotonic clock read (seconds).

    Library code times things through this (or ``Timer``/``span``) rather
    than calling ``time.time()``/``time.perf_counter()`` directly — the
    ``timing-discipline`` analysis rule enforces it (docs/INVARIANTS.md).
    Single-sourcing the clock keeps every recorded duration comparable
    (one monotonic base, never wall-clock) and keeps the door open for a
    test clock. The call is ``time.perf_counter`` today; callers must only
    assume monotonicity and seconds.
    """
    return time.perf_counter()


@contextlib.contextmanager
def span(name: str):
    """Name a stage: ops for the device trace, an annotation for the host
    timeline, and a span record for the active collector (if any)."""
    metrics.record_span(name)
    with jax.named_scope(name), jax.profiler.TraceAnnotation(name):
        yield


@contextlib.contextmanager
def trace(logdir: str, annotate: str = ""):
    """Capture a device trace under ``logdir`` (open with TensorBoard/Perfetto).

    >>> with trace("/tmp/pta_trace"):
    ...     sim.run(1000, seed=0)
    """
    with jax.profiler.trace(str(logdir)):
        if annotate:
            with jax.profiler.TraceAnnotation(annotate):
                yield
        else:
            yield


@dataclass
class Timer:
    """Accumulating wall-clock timer with device-sync semantics.

    ``block_until_ready`` is applied to whatever the timed block returns
    through ``set_result``, so the recorded time includes device execution,
    not just Python dispatch. The measurement lands even when the block
    raises (recorded in ``finally``); the device sync is skipped in that case
    only if no result was set before the raise.
    """

    times: Dict[str, List[float]] = field(default_factory=dict)

    @contextlib.contextmanager
    def section(self, name: str):
        holder = {}

        def set_result(x):
            holder["out"] = x
            return x

        t0 = time.perf_counter()
        try:
            yield set_result
        finally:
            if "out" in holder:
                jax.block_until_ready(holder["out"])
            elapsed = time.perf_counter() - t0
            self.times.setdefault(name, []).append(elapsed)
            metrics.observe(f"timer.{name}", elapsed)

    def summary(self) -> Dict[str, dict]:
        return {name: {"n": len(ts), "total_s": sum(ts),
                       "mean_s": sum(ts) / len(ts)}
                for name, ts in self.times.items() if ts}
