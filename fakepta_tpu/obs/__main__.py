"""Entry point: ``python -m fakepta_tpu.obs summarize|compare|trace|gate``."""

import sys

from .cli import main

if __name__ == "__main__":
    sys.exit(main())
