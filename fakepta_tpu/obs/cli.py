"""CLI: ``python -m fakepta_tpu.obs summarize|compare|trace|gate ...``.

``summarize`` prints one report's metric table (flight-recorder dumps get a
crash banner — spec hash, error, chunks completed); ``compare`` prints a
per-metric delta table between two reports and flags regressions
(throughput down, retraces/compile-time/cost-bytes up beyond the relative
threshold); ``trace`` exports one or more report/event-log shards as Chrome
trace-event JSON for Perfetto (multi-host shards merge into one trace with
a pid lane per host); ``gate`` bands a new bench row against the
BENCH_r*.json history (MAD over same-platform rows) and flags metrics
outside their noise band. ``compare``/``gate`` exit 0 by default even with
regressions flagged — they are diff tools; pass ``--fail-on-regression``
to gate CI on them. Exit 2 on usage/IO errors, mirroring
``fakepta_tpu.analysis``.
"""

from __future__ import annotations

import argparse
import json
import sys

from .report import RunReport, format_delta, format_summary


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="python -m fakepta_tpu.obs",
        description="inspect, diff, trace and gate ensemble-engine "
                    "RunReport artifacts (JSON-lines files written by "
                    "report.save())")
    sub = parser.add_subparsers(dest="command", required=True)

    summ = sub.add_parser("summarize", help="print one report's metrics")
    summ.add_argument("report", help="a RunReport .jsonl file (or a "
                                     "flightrec-*.json crash dump)")
    summ.add_argument("--format", choices=("text", "json"), default="text")

    comp = sub.add_parser("compare",
                          help="per-metric delta table between two reports")
    comp.add_argument("report_a", help="baseline RunReport .jsonl")
    comp.add_argument("report_b", help="candidate RunReport .jsonl")
    comp.add_argument("--rel-threshold", type=float, default=0.10,
                      help="relative change beyond which a metric moving the "
                           "wrong way is flagged (default 0.10)")
    comp.add_argument("--fail-on-regression", action="store_true",
                      help="exit 1 when any metric is flagged")

    tr = sub.add_parser(
        "trace", help="export the run timeline as Chrome trace-event JSON "
                      "(load the output at ui.perfetto.dev)")
    tr.add_argument("reports", nargs="+",
                    help="RunReport/event-log .jsonl file(s); pass every "
                         "per-host shard of a multi-process run to merge "
                         "them into one trace with a pid lane per host")
    tr.add_argument("-o", "--output", default="trace.json",
                    help="output path (default trace.json)")

    ga = sub.add_parser(
        "gate", help="band a new bench row against the BENCH_r*.json "
                     "history (MAD noise bands over same-platform rows)")
    ga.add_argument("row", help="the new row: a bench.py JSON line file, a "
                                "driver-wrapped BENCH record, or a "
                                "RunReport .jsonl (its summary is gated)")
    ga.add_argument("--history", nargs="*", default=None,
                    help="history files/globs (default: ./BENCH_r*.json)")
    ga.add_argument("--k", type=float, default=3.0,
                    help="band half-width in MADs (default 3.0)")
    ga.add_argument("--rel-floor", type=float, default=0.05,
                    help="minimum band as a fraction of the median, so a "
                         "zero-MAD history cannot flag timer noise "
                         "(default 0.05)")
    ga.add_argument("--min-history", type=int, default=2,
                    help="same-platform rows a metric needs before it "
                         "gates (default 2)")
    ga.add_argument("--fail-on-regression", action="store_true",
                    help="exit 1 when any metric leaves its band the "
                         "wrong way")
    return parser


def _cmd_summarize(args) -> int:
    rep = RunReport.load(args.report)
    if args.format == "json":
        print(json.dumps(rep.to_json(), indent=2))
        return 0
    if rep.meta.get("flightrec"):
        # a crash dump: lead with the post-mortem identity so the operator
        # sees at a glance WHICH configuration died and why
        print(f"FLIGHT RECORDER dump (crashed run)\n"
              f"  spec_hash : {rep.meta.get('spec_hash', '?')}\n"
              f"  crashed   : {rep.meta.get('crash_time', '?')}\n"
              f"  error     : {rep.meta.get('error') or '<none recorded>'}\n"
              f"  mesh      : {rep.meta.get('mesh_shape', '?')}  "
              f"chunks completed: {len(rep.chunks)}")
    print(format_summary(rep))
    return 0


def _cmd_trace(args) -> int:
    # note the submodule-direct form: the package attribute ``obs.trace`` is
    # the profiler context manager (timing.trace, kept for back-compat), so
    # the Chrome exporter must be imported as a module path
    from .trace import export as trace_export

    info = trace_export(args.reports, args.output)
    print(f"wrote {info['path']}: {info['events']} events "
          f"({info['spans']} spans, {info['processes']} process lane(s)); "
          f"load it at https://ui.perfetto.dev")
    return 0


def _cmd_gate(args) -> int:
    from . import gate as gate_mod

    new_row = gate_mod.load_row(args.row)
    hist_paths = gate_mod.resolve_history(args.history)
    # malformed / schema-partial / crashed history rows are skipped with a
    # visible warning, never a traceback: a gate that dies on one corrupt
    # BENCH row silently stops gating everything else
    history = gate_mod.load_history(
        hist_paths, warn=lambda m: print(f"warning: {m}", file=sys.stderr))
    platform = new_row.get("platform")
    n_same = len([r for r in history if r.get("platform") == platform])
    if n_same == 0:
        # an empty same-platform history cannot band anything: say so
        # plainly and exit 0 — the first accelerator round after CPU
        # stand-in rows (or a fresh clone with no BENCH_r*.json at all)
        # is the start of a trajectory, not a regression
        print(f"no comparable history: 0 same-platform "
              f"(platform={platform!r}) rows among {len(history)} loaded "
              f"history row(s); nothing to gate — this row starts the "
              f"{platform!r} trajectory")
        return 0
    results = gate_mod.gate_row(new_row, history, k=args.k,
                                rel_floor=args.rel_floor,
                                min_history=args.min_history)
    text, regressions = gate_mod.format_gate(results, platform, n_same)
    print(text)
    if regressions:
        print(f"{len(regressions)} regression(s): {', '.join(regressions)}")
        if args.fail_on_regression:
            return 1
    else:
        print("no regressions flagged")
    return 0


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    try:
        if args.command == "summarize":
            return _cmd_summarize(args)
        if args.command == "trace":
            return _cmd_trace(args)
        if args.command == "gate":
            return _cmd_gate(args)
        rep_a = RunReport.load(args.report_a)
        rep_b = RunReport.load(args.report_b)
    except (OSError, ValueError, KeyError) as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 2
    text, regressions = format_delta(rep_a, rep_b,
                                     rel_threshold=args.rel_threshold)
    print(text)
    if regressions:
        print(f"{len(regressions)} regression(s): {', '.join(regressions)}")
        if args.fail_on_regression:
            return 1
    else:
        print("no regressions flagged")
    return 0


if __name__ == "__main__":                               # pragma: no cover
    sys.exit(main())
