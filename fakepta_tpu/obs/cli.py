"""CLI: ``python -m fakepta_tpu.obs summarize|compare|trace|gate|top|alerts``.

``summarize`` prints one report's metric table (flight-recorder dumps get a
crash banner — spec hash, error, chunks completed); given SEVERAL paths
(or a directory) it interleaves every file's timestamped events into one
table with a per-replica column — the post-mortem view of a fleet's
flight-recorder dumps; ``compare`` prints a per-metric delta table between
two reports and flags regressions (throughput down,
retraces/compile-time/cost-bytes up beyond the relative threshold);
``trace`` exports one or more report/event-log shards as Chrome
trace-event JSON for Perfetto (multi-host shards merge into one trace with
a pid lane per host, request trace-ids drawn as flows); ``gate`` bands a
new bench row against the BENCH_r*.json history (MAD over same-platform
rows) and flags metrics outside their noise band; ``top`` renders the
fleet telemetry rollup as a refreshing terminal table from a live replica
socket (``host:port``, polled over the ``telemetry`` protocol kind) or a
saved ``fakepta_tpu.obs/2`` log; ``alerts`` prints the active and
historical threshold alerts from the same sources.
``compare``/``gate`` exit 0 by default even with regressions flagged —
they are diff tools; pass ``--fail-on-regression`` to gate CI on them.
Exit 2 on usage/IO errors, mirroring ``fakepta_tpu.analysis``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path
from typing import List

from .metrics import EventLog
from .report import RunReport, format_delta, format_summary


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="python -m fakepta_tpu.obs",
        description="inspect, diff, trace and gate ensemble-engine "
                    "RunReport artifacts (JSON-lines files written by "
                    "report.save())")
    sub = parser.add_subparsers(dest="command", required=True)

    summ = sub.add_parser("summarize", help="print one report's metrics, "
                                            "or interleave several")
    summ.add_argument("report", nargs="+",
                      help="RunReport .jsonl file(s) or flightrec-*.json "
                           "crash dump(s); several paths (or a directory "
                           "of them) interleave by timestamp with a "
                           "per-replica column")
    summ.add_argument("--format", choices=("text", "json"), default="text")

    comp = sub.add_parser("compare",
                          help="per-metric delta table between two reports")
    comp.add_argument("report_a", help="baseline RunReport .jsonl")
    comp.add_argument("report_b", help="candidate RunReport .jsonl")
    comp.add_argument("--rel-threshold", type=float, default=0.10,
                      help="relative change beyond which a metric moving the "
                           "wrong way is flagged (default 0.10)")
    comp.add_argument("--fail-on-regression", action="store_true",
                      help="exit 1 when any metric is flagged")

    tr = sub.add_parser(
        "trace", help="export the run timeline as Chrome trace-event JSON "
                      "(load the output at ui.perfetto.dev)")
    tr.add_argument("reports", nargs="+",
                    help="RunReport/event-log .jsonl file(s); pass every "
                         "per-host shard of a multi-process run to merge "
                         "them into one trace with a pid lane per host")
    tr.add_argument("-o", "--output", default="trace.json",
                    help="output path (default trace.json)")

    ga = sub.add_parser(
        "gate", help="band a new bench row against the BENCH_r*.json "
                     "history (MAD noise bands over same-platform, "
                     "same-scenario rows)")
    ga.add_argument("row", help="the new row: a bench.py JSON line file, a "
                                "driver-wrapped BENCH record, or a "
                                "RunReport .jsonl (its summary is gated)")
    ga.add_argument("--history", nargs="*", default=None,
                    help="history files/globs (default: ./BENCH_r*.json)")
    ga.add_argument("--k", type=float, default=3.0,
                    help="band half-width in MADs (default 3.0)")
    ga.add_argument("--rel-floor", type=float, default=0.05,
                    help="minimum band as a fraction of the median, so a "
                         "zero-MAD history cannot flag timer noise "
                         "(default 0.05)")
    ga.add_argument("--min-history", type=int, default=2,
                    help="same-platform rows a metric needs before it "
                         "gates (default 2)")
    ga.add_argument("--fail-on-regression", action="store_true",
                    help="exit 1 when any metric leaves its band the "
                         "wrong way")

    def _add_telemetry_source(p):
        p.add_argument("source",
                       help="a live replica/router socket as HOST:PORT "
                            "(polled over the `telemetry` protocol kind) "
                            "or a saved fakepta_tpu.obs/2 event log")

    top = sub.add_parser(
        "top", help="refreshing terminal table of the fleet telemetry "
                    "rollup (per-replica health, qps, p50/p99, queue "
                    "depth, cache hit rate, breaker state)")
    _add_telemetry_source(top)
    top.add_argument("--interval", type=float, default=1.0,
                     help="refresh interval in seconds (default 1)")
    top.add_argument("--iterations", type=int, default=None,
                     help="render this many frames then exit "
                          "(default: run until ^C; a saved log renders "
                          "exactly one frame)")

    al = sub.add_parser(
        "alerts", help="print the telemetry plane's threshold alerts "
                       "(active excursions + the fired-alert history)")
    _add_telemetry_source(al)
    al.add_argument("--format", choices=("text", "json"), default="text")
    return parser


def _expand_report_paths(paths) -> List[str]:
    """CLI paths -> concrete files: a directory expands to every .json /
    .jsonl it holds (sorted — the fleet's flightrec dump convention)."""
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(str(f) for f in sorted(Path(p).iterdir())
                       if f.suffix in (".json", ".jsonl"))
        else:
            out.append(str(p))
    if not out:
        raise ValueError("no report files found")
    return out


def _interleave_rows(paths: List[str]) -> List[dict]:
    """Timestamped event rows from several artifacts, merged.

    Each file contributes its flight-recorder events (``t_mono_s``),
    timeline spans (``t0``), and telemetry/alert lines (``t``) tagged with
    a replica label — ``meta.replica_id`` when the artifact carries one,
    else ``p<process_index>``, else the file stem. Per-file clocks are
    run-relative, which is what a fleet post-mortem needs: the dumps were
    cut at the same wall moment, so lanes align at the tail.
    """
    rows: List[dict] = []
    for path in paths:
        log = EventLog.load(path)
        meta = log.meta or {}
        replica = str(meta.get("replica_id")
                      or (f"p{meta['process_index']}"
                          if "process_index" in meta else Path(path).stem))
        for line in log.lines:
            kind = line.get("kind")
            t = None
            if kind == "event":
                t, name = line.get("t_mono_s"), line.get("name", "?")
                detail = line.get("attrs") or {}
            elif kind == "tl":
                t, name = line.get("t0"), line.get("name", "?")
                detail = {k: v for k, v in line.items()
                          if k not in ("kind", "name", "t0")}
            elif kind in ("telemetry", "alert"):
                t, name = line.get("t"), kind
                detail = {k: v for k, v in line.items()
                          if k not in ("kind", "t")}
            if t is None:
                continue
            rows.append({"t": float(t), "replica": replica, "name": name,
                         "detail": detail})
    rows.sort(key=lambda r: (r["t"], r["replica"]))
    return rows


def _summarize_many(paths: List[str], fmt: str) -> int:
    rows = _interleave_rows(paths)
    if fmt == "json":
        print(json.dumps({"files": len(paths), "events": rows}, indent=2))
        return 0
    print(f"{len(paths)} artifact(s), {len(rows)} timestamped event(s)")
    print(f"{'t_s':>12}  {'replica':<14} {'event':<32} detail")
    for r in rows:
        detail = ", ".join(
            f"{k}={v}" for k, v in sorted(r["detail"].items())
            if not isinstance(v, (dict, list)))[:120]
        print(f"{r['t']:>12.6f}  {r['replica']:<14} {r['name']:<32} "
              f"{detail}")
    return 0


def _cmd_summarize(args) -> int:
    paths = _expand_report_paths(args.report)
    if len(paths) > 1:
        return _summarize_many(paths, args.format)
    rep = RunReport.load(paths[0])
    if args.format == "json":
        print(json.dumps(rep.to_json(), indent=2))
        return 0
    if rep.meta.get("flightrec"):
        # a crash dump: lead with the post-mortem identity so the operator
        # sees at a glance WHICH configuration died and why
        print(f"FLIGHT RECORDER dump (crashed run)\n"
              f"  spec_hash : {rep.meta.get('spec_hash', '?')}\n"
              f"  crashed   : {rep.meta.get('crash_time', '?')}\n"
              f"  error     : {rep.meta.get('error') or '<none recorded>'}\n"
              f"  mesh      : {rep.meta.get('mesh_shape', '?')}  "
              f"chunks completed: {len(rep.chunks)}")
    print(format_summary(rep))
    return 0


def _cmd_trace(args) -> int:
    # note the submodule-direct form: the package attribute ``obs.trace`` is
    # the profiler context manager (timing.trace, kept for back-compat), so
    # the Chrome exporter must be imported as a module path
    from .trace import export as trace_export

    info = trace_export(args.reports, args.output)
    print(f"wrote {info['path']}: {info['events']} events "
          f"({info['spans']} spans, {info['processes']} process lane(s)); "
          f"load it at https://ui.perfetto.dev")
    return 0


def _telemetry_fetch(source: str):
    """A zero-arg rollup fetcher for ``top``/``alerts``.

    ``HOST:PORT`` polls a live serve socket over the ``telemetry``
    protocol kind, feeding a CLI-local aggregator (same watermark/window
    logic the fleet router runs); a path loads a saved
    ``fakepta_tpu.obs/2`` log once. Returns ``(fetch, live)``.
    """
    from . import telemetry as telemetry_mod

    host, sep, port = source.rpartition(":")
    if sep and port.isdigit() and not os.path.exists(source):
        import socket as socket_mod

        conn = socket_mod.create_connection((host or "127.0.0.1",
                                             int(port)), timeout=10.0)
        conn.settimeout(10.0)
        rfile = conn.makefile("rb")
        agg = telemetry_mod.TelemetryAggregator()
        state = {"id": 0}

        def fetch() -> dict:
            state["id"] += 1
            conn.sendall((json.dumps({"id": state["id"],
                                      "kind": "telemetry"}) + "\n")
                         .encode())
            line = rfile.readline(8 * 1024 * 1024)
            if not line:
                raise EOFError("telemetry source closed the connection")
            reply = json.loads(line.decode("utf-8", "replace"))
            snap = reply.get("telemetry") or {}
            if snap:
                agg.ingest(source, snap)
            return agg.rollup()

        return fetch, True

    log = EventLog.load(source)

    def fetch_file() -> dict:
        return telemetry_mod.rollup_from_event_log(log)

    return fetch_file, False


def _cmd_top(args) -> int:
    from . import topview

    fetch, live = _telemetry_fetch(args.source)
    iterations = args.iterations if live else 1
    frames = topview.run_top(fetch, interval_s=args.interval,
                             iterations=iterations)
    return 0 if frames else 1


def _cmd_alerts(args) -> int:
    fetch, _live = _telemetry_fetch(args.source)
    rollup = fetch()
    alerts = rollup.get("alerts", [])
    if args.format == "json":
        print(json.dumps({"alerts": alerts}, indent=2))
        return 0
    if not alerts:
        print("no alerts")
        return 0
    for a in alerts:
        detail = ", ".join(f"{k}={v}" for k, v in sorted(a.items())
                           if k not in ("rule", "replica"))
        print(f"{a.get('rule', '?'):<28} {a.get('replica', '?'):<14} "
              f"{detail}")
    return 0


def _cmd_gate(args) -> int:
    from . import gate as gate_mod

    new_row = gate_mod.load_row(args.row)
    hist_paths = gate_mod.resolve_history(args.history)
    # malformed / schema-partial / crashed history rows are skipped with a
    # visible warning, never a traceback: a gate that dies on one corrupt
    # BENCH row silently stops gating everything else
    history = gate_mod.load_history(
        hist_paths, warn=lambda m: print(f"warning: {m}", file=sys.stderr))
    platform = new_row.get("platform")
    scenario = new_row.get("scenario")
    n_same = len([r for r in history if r.get("platform") == platform
                  and r.get("scenario") == scenario])
    if n_same == 0:
        # an empty same-platform (and, for golden rows, same-scenario)
        # history cannot band anything: say so plainly and exit 0 — the
        # first accelerator round after CPU stand-in rows (or the first
        # golden run of a new scenario) is the start of a trajectory,
        # not a regression
        what = (f"platform={platform!r}"
                + (f", scenario={scenario!r}" if scenario else ""))
        kind = "same-platform" + (", same-scenario" if scenario else "")
        print(f"no comparable history: 0 {kind} ({what}) rows among "
              f"{len(history)} loaded history row(s); nothing to gate — "
              f"this row starts that trajectory")
        return 0
    results = gate_mod.gate_row(new_row, history, k=args.k,
                                rel_floor=args.rel_floor,
                                min_history=args.min_history)
    text, regressions = gate_mod.format_gate(results, platform, n_same)
    print(text)
    if regressions:
        print(f"{len(regressions)} regression(s): {', '.join(regressions)}")
        if args.fail_on_regression:
            return 1
    else:
        print("no regressions flagged")
    return 0


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    try:
        if args.command == "summarize":
            return _cmd_summarize(args)
        if args.command == "trace":
            return _cmd_trace(args)
        if args.command == "gate":
            return _cmd_gate(args)
        if args.command == "top":
            return _cmd_top(args)
        if args.command == "alerts":
            return _cmd_alerts(args)
        rep_a = RunReport.load(args.report_a)
        rep_b = RunReport.load(args.report_b)
    except (OSError, ValueError, KeyError) as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 2
    text, regressions = format_delta(rep_a, rep_b,
                                     rel_threshold=args.rel_threshold)
    print(text)
    if regressions:
        print(f"{len(regressions)} regression(s): {', '.join(regressions)}")
        if args.fail_on_regression:
            return 1
    else:
        print("no regressions flagged")
    return 0


if __name__ == "__main__":                               # pragma: no cover
    sys.exit(main())
