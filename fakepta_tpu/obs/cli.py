"""CLI: ``python -m fakepta_tpu.obs summarize|compare <report.jsonl>...``.

``summarize`` prints one report's metric table; ``compare`` prints a
per-metric delta table between two reports and flags regressions
(throughput down, retraces/compile-time/cost-bytes up beyond the relative
threshold). ``compare`` exits 0 by default even with regressions flagged —
it is a diff tool; pass ``--fail-on-regression`` to gate CI on it. Exit 2 on
usage/IO errors, mirroring ``fakepta_tpu.analysis``.
"""

from __future__ import annotations

import argparse
import json
import sys

from .report import RunReport, format_delta, format_summary


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="python -m fakepta_tpu.obs",
        description="inspect and diff ensemble-engine RunReport artifacts "
                    "(JSON-lines files written by report.save())")
    sub = parser.add_subparsers(dest="command", required=True)

    summ = sub.add_parser("summarize", help="print one report's metrics")
    summ.add_argument("report", help="a RunReport .jsonl file")
    summ.add_argument("--format", choices=("text", "json"), default="text")

    comp = sub.add_parser("compare",
                          help="per-metric delta table between two reports")
    comp.add_argument("report_a", help="baseline RunReport .jsonl")
    comp.add_argument("report_b", help="candidate RunReport .jsonl")
    comp.add_argument("--rel-threshold", type=float, default=0.10,
                      help="relative change beyond which a metric moving the "
                           "wrong way is flagged (default 0.10)")
    comp.add_argument("--fail-on-regression", action="store_true",
                      help="exit 1 when any metric is flagged")
    return parser


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    try:
        if args.command == "summarize":
            rep = RunReport.load(args.report)
            if args.format == "json":
                print(json.dumps(rep.to_json(), indent=2))
            else:
                print(format_summary(rep))
            return 0
        rep_a = RunReport.load(args.report_a)
        rep_b = RunReport.load(args.report_b)
    except (OSError, ValueError, KeyError) as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 2
    text, regressions = format_delta(rep_a, rep_b,
                                     rel_threshold=args.rel_threshold)
    print(text)
    if regressions:
        print(f"{len(regressions)} regression(s): {', '.join(regressions)}")
        if args.fail_on_regression:
            return 1
    else:
        print("no regressions flagged")
    return 0


if __name__ == "__main__":                               # pragma: no cover
    sys.exit(main())
