"""fakepta_tpu.obs — run telemetry for the ensemble engine.

Structured observability spanning the metrics core (counters / gauges /
timing histograms + a schema-stable JSON-lines sink, ``metrics``), trace
spans and device-synced timing (``timing``; absorbs and supersedes
``fakepta_tpu.utils.profiling``), and the per-run :class:`RunReport`
artifact every ``EnsembleSimulator.run()`` attaches, with a CLI to diff two
runs (``python -m fakepta_tpu.obs summarize|compare``). See
docs/OBSERVABILITY.md.

Everything here is host-side code. The one contract: obs hooks never
introduce host syncs into jitted scopes — spans execute at trace time only,
and telemetry reads happen at chunk boundaries where the engine already
fetches (docs/INVARIANTS.md).
"""

from .metrics import (SCHEMA, Collector, EventLog, active, collect, count,
                      event, gauge, observe, record_span,
                      subscribe_jax_monitoring)
from .report import RunReport, format_delta, format_summary
from .timing import Timer, annotation, span, trace

__all__ = [
    "SCHEMA", "Collector", "EventLog", "RunReport", "Timer", "annotation",
    "active", "collect", "count", "event", "format_delta", "format_summary",
    "gauge", "observe", "record_span", "span", "subscribe_jax_monitoring",
    "trace",
]
