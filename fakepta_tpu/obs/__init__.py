"""fakepta_tpu.obs — run telemetry for the ensemble engine.

Structured observability spanning the metrics core (counters / gauges /
timing histograms + a schema-stable JSON-lines sink, ``metrics``), trace
spans and device-synced timing (``timing``; absorbs and supersedes
``fakepta_tpu.utils.profiling``), the per-run :class:`RunReport` artifact
every ``EnsembleSimulator.run()`` attaches, the run-timeline Chrome-trace
exporter (``trace`` module — Perfetto-viewable pipeline overlap), HBM
watermark telemetry (``memwatch``), the always-on crash flight recorder
(``flightrec``), and the BENCH-trajectory regression gate (``gate``), with
a CLI over all of it (``python -m fakepta_tpu.obs
summarize|compare|trace|gate``). See docs/OBSERVABILITY.md.

Everything here is host-side code. The one contract: obs hooks never
introduce host syncs into jitted scopes — spans execute at trace time only,
and telemetry reads happen at chunk boundaries where the engine already
fetches (docs/INVARIANTS.md).

Naming note: the package attribute ``obs.trace`` is the *profiler* context
manager (``timing.trace``, long part of the public API); the Chrome
trace-event exporter module is reached as ``obs.tracefmt`` or
``fakepta_tpu.obs.trace`` via a module-path import (``from
fakepta_tpu.obs.trace import build_trace``). The imports below are ordered
so the function wins the attribute.
"""

from . import flightrec, gate, memwatch, promfmt, telemetry, topview
from . import trace as tracefmt
from .metrics import (METRIC_NAMES, SCHEMA, SCHEMA_V2, Collector, EventLog,
                      active, collect, count, event, gauge, observe,
                      record_span, subscribe_jax_monitoring)
from .report import (RunReport, format_delta, format_summary, metric_exempt,
                     metric_higher_is_better)
from .timing import Timer, annotation, now, span, trace

__all__ = [
    "METRIC_NAMES", "SCHEMA", "SCHEMA_V2", "Collector", "EventLog",
    "RunReport", "Timer", "annotation",
    "active", "collect", "count", "event", "flightrec", "format_delta",
    "format_summary", "gate", "gauge", "memwatch", "metric_exempt",
    "metric_higher_is_better", "now", "observe", "promfmt", "record_span",
    "span", "subscribe_jax_monitoring", "telemetry", "topview", "trace",
    "tracefmt",
]
