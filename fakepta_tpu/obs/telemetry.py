"""Live distributed telemetry plane (docs/OBSERVABILITY.md "Telemetry").

Three pieces, replica to fleet:

- :class:`TelemetryPublisher` — per-replica. Snapshots registered sources
  (pool SLO counters, warm-pool occupancy, stream append latencies, plus
  the process-wide :func:`publish` live gauges: sampler segment progress,
  refresh-gate decisions, ``peak_hbm_bytes``) into a bounded ring. A
  snapshot is a plain JSON-able dict stamped with a per-publisher ``seq``
  and a monotonic ``t`` — the watermark ingredients.
- :class:`TelemetryAggregator` — fleet-level. Ingests snapshots keyed by
  replica id (the fleet's :class:`~fakepta_tpu.serve.health.HealthMonitor`
  piggybacks the scrape on its heartbeat cadence — same mux'd connection,
  zero new sockets), keeps a windowed per-replica ring, and rolls it up
  keyed replica × spec-hash × stream-name. The merge is watermark-correct:
  a snapshot with ``seq`` at or below the replica's watermark is dropped
  (duplicates / reordered scrapes), a re-joining replica's fresh ``seq``
  epoch resets the baseline instead of producing negative rates, and a
  retired replica's last rollup is kept frozen under ``retired``.
- :class:`AlertRules` — threshold rules over the rollup (p99 over SLO,
  heartbeat-miss streak, append-latency regression, HBM watermark).
  Edge-triggered: each rule fires one flight-recorder note when it trips
  and re-arms when the condition clears.

Everything here is host-side dict arithmetic — no jax, no sockets. The
serve layer owns the wire (``telemetry``/``metrics`` protocol kinds in
``serve/cli.py``) and the scrape cadence (``serve/health.py``).
"""

from __future__ import annotations

import collections
import threading
from typing import Callable, Dict, List, Optional

from ..tune import defaults as tune_defaults
from . import flightrec, metrics
from .timing import now

#: schema tag stamped on telemetry event-log lines (the ``telemetry`` and
#: ``alert`` record kinds ride the ``fakepta_tpu.obs/2`` era)
SCHEMA = metrics.SCHEMA_V2


# --- process-wide live gauges ----------------------------------------------
# Lightweight cross-layer publishing: deep layers (sampler segment loop,
# refresh gate, memwatch) set a value; the publisher snapshots the table.
# One dict store under a lock per publish — cheap enough for append paths.

_live_lock = threading.Lock()
_live: Dict[str, float] = {}


def publish(name: str, value) -> None:
    """Set a live gauge the next telemetry snapshot will carry."""
    with _live_lock:
        _live[name] = value


def live_gauges() -> Dict[str, float]:
    """Snapshot of the process-wide live-gauge table."""
    with _live_lock:
        return dict(_live)


def clear_live_gauges() -> None:
    """Test hook: forget all live gauges (process-global state)."""
    with _live_lock:
        _live.clear()


class TelemetryPublisher:
    """Per-replica snapshot ring over registered sources.

    Sources are zero-arg callables returning JSON-able values; a failing
    source is recorded (``telemetry.scrape_errors``) and skipped, never
    propagated — telemetry is best-effort and must not take the serving
    path down with it.
    """

    def __init__(self, replica_id: str = "",
                 ring_size: int = tune_defaults.TELEMETRY_RING_SIZE):
        self.replica_id = str(replica_id)
        self._lock = threading.Lock()
        self._sources: Dict[str, Callable[[], object]] = {}
        self._ring = collections.deque(maxlen=int(ring_size))
        self._seq = 0
        #: seq epoch: lets an aggregator distinguish a restarted publisher
        #: (fresh counters) from a reordered scrape of the old one
        self.epoch = flightrec.spec_hash({"kind": "telemetry-epoch",
                                          "replica": self.replica_id,
                                          "nonce": id(self)})

    def add_source(self, name: str, fn: Callable[[], object]) -> None:
        with self._lock:
            self._sources[name] = fn

    def snapshot(self) -> dict:
        """Build one snapshot, append it to the ring, and return it."""
        with self._lock:
            self._seq += 1
            seq = self._seq
            sources = list(self._sources.items())
        snap = {"seq": seq, "epoch": self.epoch, "t": now(),
                "replica": self.replica_id}
        for name, fn in sources:
            try:
                snap[name] = fn()
            except Exception as exc:   # noqa: BLE001 — recorded, not raised
                metrics.count("telemetry.scrape_errors")
                flightrec.note("telemetry_source_failed", source=name,
                               error=repr(exc)[:160])
        snap["live"] = live_gauges()
        metrics.count("telemetry.scrapes")
        with self._lock:
            self._ring.append(snap)
        return snap

    def ring(self) -> List[dict]:
        with self._lock:
            return list(self._ring)


class _ReplicaWindow:
    """One replica's snapshot window inside the aggregator."""

    __slots__ = ("ring", "watermark", "epoch", "health")

    def __init__(self, ring_size: int):
        self.ring = collections.deque(maxlen=ring_size)
        self.watermark = 0          # highest seq merged this epoch
        self.epoch = None
        self.health = {}            # last health-ladder info from the scraper


class TelemetryAggregator:
    """Fleet-level windowed rollups over scraped replica snapshots."""

    def __init__(self, window_s: float = tune_defaults.TELEMETRY_WINDOW_S,
                 ring_size: int = tune_defaults.TELEMETRY_RING_SIZE,
                 alert_rules: Optional["AlertRules"] = None):
        self.window_s = float(window_s)
        self.ring_size = int(ring_size)
        self._lock = threading.Lock()
        self._replicas: Dict[str, _ReplicaWindow] = {}
        self._retired: Dict[str, dict] = {}
        self.alerts = alert_rules if alert_rules is not None else AlertRules()
        self.ingested = 0
        self.dropped_stale = 0

    # -- ingestion (the heartbeat scraper's call) --------------------------
    def ingest(self, replica_id: str, snap: dict,
               health: Optional[dict] = None) -> bool:
        """Merge one scraped snapshot; returns whether it advanced the
        replica's watermark (False = stale duplicate, dropped)."""
        rid = str(replica_id)
        seq = int(snap.get("seq", 0))
        epoch = snap.get("epoch")
        with self._lock:
            win = self._replicas.get(rid)
            if win is None:
                win = self._replicas[rid] = _ReplicaWindow(self.ring_size)
                # a re-join after retire supersedes the frozen rollup
                self._retired.pop(rid, None)
            if epoch != win.epoch:
                # restarted publisher (new process / re-join): fresh seq
                # epoch, fresh baseline — never a negative-rate merge
                win.epoch = epoch
                win.watermark = 0
                win.ring.clear()
            if seq <= win.watermark:
                self.dropped_stale += 1
                return False
            win.watermark = seq
            win.ring.append(snap)
            if health is not None:
                win.health = dict(health)
            self.ingested += 1
        self.alerts.evaluate(self.rollup())
        return True

    def retire(self, replica_id: str) -> None:
        """Freeze a draining replica's last rollup (watermark-correct
        retirement: its history leaves the live window but is not lost)."""
        rid = str(replica_id)
        with self._lock:
            win = self._replicas.pop(rid, None)
        if win is not None and win.ring:
            self._retired[rid] = self._rollup_one(rid, win)

    # -- rollups -----------------------------------------------------------
    def _window(self, win: _ReplicaWindow) -> List[dict]:
        snaps = list(win.ring)
        if not snaps:
            return []
        horizon = snaps[-1].get("t", 0.0) - self.window_s
        return [s for s in snaps if s.get("t", 0.0) >= horizon]

    def _rollup_one(self, rid: str, win: _ReplicaWindow) -> dict:
        snaps = self._window(win)
        if not snaps:
            return {"replica": rid, "snapshots": 0}
        first, last = snaps[0], snaps[-1]
        slo0, slo1 = first.get("slo", {}), last.get("slo", {})

        def _slo(key, default=0.0):
            # the pool's slo_summary prefixes its metric names (the bench
            # schema's ``serve_*`` family); bare names are the fallback so
            # hand-rolled publishers stay ingestible
            return slo1.get("serve_" + key, slo1.get(key, default))

        dt = max(last.get("t", 0.0) - first.get("t", 0.0), 1e-9)
        dreq = (slo1.get("serve_requests", 0)
                - slo0.get("serve_requests", 0))
        row = {
            "replica": rid,
            "snapshots": len(snaps),
            "seq": last.get("seq", 0),
            "t": last.get("t", 0.0),
            "health": win.health.get("state", "unknown"),
            "heartbeat_misses": win.health.get("misses", 0),
            "breaker_open": bool(win.health.get("breaker_open", False)),
            # window qps: counter delta over the window's monotonic span
            # (one snapshot = no delta yet, report the pool's own figure)
            "qps": (dreq / dt if len(snaps) > 1
                    else _slo("qps_per_chip")),
            "p50_ms": _slo("p50_ms"),
            "p99_ms": _slo("p99_ms"),
            "queue_depth": slo1.get("queue_depth", 0),
            "requests": slo1.get("serve_requests", 0),
            "failed": slo1.get("serve_failed", 0),
        }
        pool = last.get("pool", {})
        if pool:
            entries = pool.get("entries", 0)
            row["warm_entries"] = entries
            row["warm_max"] = pool.get("max_entries", 0)
            builds = pool.get("builds", 0)
            # cache hit rate: fraction of warm lookups that did not build
            hits = max(slo1.get("serve_dispatches", 0) - builds, 0)
            denom = max(slo1.get("serve_dispatches", 0), 1)
            row["cache_hit_rate"] = hits / denom
            row["specs"] = pool.get("specs", {})
        streams = last.get("streams", {})
        if streams:
            row["streams"] = streams
        live = last.get("live", {})
        if live:
            row["live"] = {k: v for k, v in sorted(live.items())}
            if "obs.peak_hbm_bytes" in live:
                row["peak_hbm_bytes"] = live["obs.peak_hbm_bytes"]
        # append-latency regression input: window baseline vs latest
        lat = [s.get("streams", {}) for s in snaps]
        base = [v.get("append_mean_ms") for d in lat[:max(len(lat) // 2, 1)]
                for v in d.values() if v.get("append_mean_ms")]
        tail = [v.get("append_mean_ms") for d in lat[len(lat) // 2:]
                for v in d.values() if v.get("append_mean_ms")]
        if base and tail:
            row["append_baseline_ms"] = sum(base) / len(base)
            row["append_recent_ms"] = sum(tail) / len(tail)
        return row

    def rollup(self) -> dict:
        """The fleet view: per-replica rows plus fleet totals, ready for
        ``obs top``, the Prometheus exposition, and the alert rules."""
        with self._lock:
            rows = {rid: self._rollup_one(rid, win)
                    for rid, win in self._replicas.items()}
            retired = dict(self._retired)
            counts = {"ingested": self.ingested,
                      "dropped_stale": self.dropped_stale}
        fleet = {
            "replicas": len(rows),
            "qps": sum(r.get("qps", 0.0) for r in rows.values()),
            "queue_depth": sum(r.get("queue_depth", 0)
                               for r in rows.values()),
            "p99_ms_max": max([r.get("p99_ms", 0.0)
                               for r in rows.values()] or [0.0]),
        }
        return {"schema": SCHEMA, "fleet": dict(fleet, **counts),
                "per_replica": rows, "retired": retired,
                "alerts": self.alerts.active()}

    # -- persistence (the obs/2 event-log surface) -------------------------
    def to_event_log(self, meta: Optional[dict] = None):
        """Serialize the live window as a ``fakepta_tpu.obs/2`` event log:
        one ``telemetry`` line per snapshot (oldest first), one ``alert``
        line per firing, plus a rollup summary."""
        log = metrics.EventLog(meta=dict(meta or {}, telemetry=True),
                               schema=SCHEMA)
        with self._lock:
            items = sorted(
                ((s.get("t", 0.0), rid, s)
                 for rid, win in self._replicas.items() for s in win.ring),
                key=lambda it: (it[0], it[1]))
        for t, rid, snap in items:
            # t is lifted to the line level so interleaving tools (`obs
            # summarize` over many artifacts) can sort without opening snaps
            log.append("telemetry", t=t, replica=rid, snap=snap)
        for alert in self.alerts.log:
            log.append("alert", **alert)
        return log

    def save(self, path, meta: Optional[dict] = None) -> str:
        return self.to_event_log(meta).save(
            path, summary={"rollup": self.rollup()})


def rollup_from_event_log(log) -> dict:
    """Rebuild a rollup from a saved obs/2 telemetry log (the file-fed
    path of ``obs top`` / ``obs alerts``)."""
    summary = log.summary() or {}
    if "rollup" in summary:
        return summary["rollup"]
    agg = TelemetryAggregator()
    for line in log.lines:
        if line.get("kind") == "telemetry":
            agg.ingest(line.get("replica", ""), line.get("snap", {}))
    return agg.rollup()


class AlertRules:
    """Threshold alert rules over an aggregator rollup (edge-triggered).

    Rules (docs/OBSERVABILITY.md "Alert rules"):

    - ``p99_over_slo``: a replica's windowed p99 exceeds the SLO bound;
    - ``heartbeat_miss_streak``: consecutive probe misses at/over the
      streak threshold (the pre-breaker early warning);
    - ``append_latency_regression``: the window's recent mean append
      latency exceeds ``regression_x`` times the window baseline;
    - ``hbm_watermark``: ``peak_hbm_bytes`` crosses the watermark
      fraction of the per-device budget.

    Each (rule, replica) pair fires ONE flight-recorder note per
    excursion and re-arms when the condition clears — alerting on every
    scrape of a sustained breach would bury the flight recorder's bounded
    ring in duplicates.
    """

    def __init__(self,
                 p99_slo_ms: float = tune_defaults.ALERT_P99_SLO_MS,
                 miss_streak: int =
                 tune_defaults.ALERT_HEARTBEAT_MISS_STREAK,
                 regression_x: float =
                 tune_defaults.ALERT_APPEND_REGRESSION_X,
                 hbm_frac: float = tune_defaults.ALERT_HBM_WATERMARK_FRAC,
                 hbm_budget_bytes: float =
                 tune_defaults.DEFAULT_BYTES_BUDGET):
        self.p99_slo_ms = float(p99_slo_ms)
        self.miss_streak = int(miss_streak)
        self.regression_x = float(regression_x)
        self.hbm_frac = float(hbm_frac)
        self.hbm_budget_bytes = float(hbm_budget_bytes)
        self._lock = threading.Lock()
        self._firing: Dict[tuple, dict] = {}
        #: full firing history (bounded like the publisher rings)
        self.log = collections.deque(
            maxlen=tune_defaults.TELEMETRY_RING_SIZE)

    def _conditions(self, row: dict):
        rid = row.get("replica", "")
        p99 = row.get("p99_ms", 0.0)
        if p99 > self.p99_slo_ms:
            yield ("p99_over_slo", rid,
                   {"p99_ms": p99, "slo_ms": self.p99_slo_ms})
        misses = row.get("heartbeat_misses", 0)
        if misses >= self.miss_streak:
            yield ("heartbeat_miss_streak", rid,
                   {"misses": misses, "streak": self.miss_streak})
        base = row.get("append_baseline_ms")
        recent = row.get("append_recent_ms")
        if base and recent and recent > self.regression_x * base:
            yield ("append_latency_regression", rid,
                   {"baseline_ms": base, "recent_ms": recent,
                    "regression_x": self.regression_x})
        hbm = row.get("peak_hbm_bytes")
        if hbm and hbm > self.hbm_frac * self.hbm_budget_bytes:
            yield ("hbm_watermark", rid,
                   {"peak_hbm_bytes": hbm,
                    "watermark_bytes": self.hbm_frac
                     * self.hbm_budget_bytes})

    def evaluate(self, rollup: dict) -> List[dict]:
        """Run every rule over the rollup; returns newly-fired alerts."""
        fired = []
        seen = set()
        for row in rollup.get("per_replica", {}).values():
            for rule, rid, detail in self._conditions(row):
                key = (rule, rid)
                seen.add(key)
                with self._lock:
                    if key in self._firing:
                        continue
                    alert = dict(detail, rule=rule, replica=rid,
                                 t=row.get("t", 0.0))
                    self._firing[key] = alert
                    self.log.append(alert)
                fired.append(alert)
                metrics.count("telemetry.alerts")
                flightrec.note("telemetry_alert", rule=rule, replica=rid,
                               **{k: v for k, v in detail.items()})
        with self._lock:   # re-arm rules whose condition cleared
            for key in [k for k in self._firing if k not in seen]:
                del self._firing[key]
        return fired

    def active(self) -> List[dict]:
        with self._lock:
            return [dict(a) for a in self._firing.values()]
