"""HBM watermark telemetry: allocator sampling + packed-buffer accounting.

Two complementary views of device memory, both feeding
``RunReport.memory``:

- :class:`HbmSampler` — a low-rate background sampler of the backend's
  allocator stats (``device.memory_stats()``), aggregated **max over local
  devices** and over samples. The one-shot capture it replaces sampled a
  single device at run *end*, which both underreports multi-chip peaks and
  misses any transient high-water mark between chunk boundaries. On
  backends without allocator stats (XLA:CPU) the sampler detects that at
  construction and never starts a thread — the stand-in rounds pay zero
  cost.
- :class:`PackedLedger` — per-chunk live-buffer accounting of the engine's
  packed output buffers, the arrays the async pipeline's donated-scratch
  ring is supposed to bound (docs/PERFORMANCE.md: "peak HBM holds ``depth``
  packed buffers regardless of the chunk count"). The ledger counts fresh
  device allocations vs recycles, verifies each recycled buffer really was
  consumed by donation (``is_deleted`` — XLA invalidates a donated input at
  dispatch), and :meth:`PackedLedger.check` raises if the runtime evidence
  ever exceeds the ``depth``-buffers bound. PR 5's headline memory claim is
  now asserted on every pipelined run instead of trusted.

``RunReport.memory["peak_hbm_bytes"]`` is the allocator watermark where the
backend exposes one, else the ledger's model (the chunk program's static
reservation plus the extra live packed buffers beyond the one the
reservation already counts).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

# allocator keys worth keeping, max-aggregated over local devices
STAT_KEYS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
             "largest_alloc_size")

# low-rate: ~20 Hz is dense enough to catch per-chunk transients (flagship
# chunks are tens of ms at the slowest) while the sample itself is a cheap
# local PJRT call — the thread is idle sleep otherwise
SAMPLE_INTERVAL_S = 0.05


def local_device_stats(devices) -> Dict[str, int]:
    """Max-over-local-devices allocator stats (empty where unsupported).

    ``devices`` is any iterable of jax devices (e.g. ``mesh.devices.flat``);
    non-addressable devices (other hosts' chips in a multi-process mesh)
    and backends without ``memory_stats`` are skipped. Aggregation is
    ``max`` per key: the watermark that matters is the worst chip, and a
    multi-chip mesh underreports peak HBM by up to ``n_devices``x if only
    one device is sampled.
    """
    out: Dict[str, int] = {}
    for d in devices:
        try:
            if not getattr(d, "addressable", True):
                continue
            stats = d.memory_stats()
        except Exception:
            continue
        if not stats:
            continue
        for k in STAT_KEYS:
            if k in stats:
                out[k] = max(out.get(k, 0), int(stats[k]))
    return out


class HbmSampler:
    """Background allocator-watermark sampler over the run's local devices.

    ``start()`` probes once: if no local device exposes allocator stats the
    sampler stays disabled (no thread). Otherwise a daemon thread samples at
    :data:`SAMPLE_INTERVAL_S` and max-merges into the running watermark;
    ``stop()`` joins the thread, takes one final sample, and returns the
    aggregate stats dict (plus ``hbm_samples``, the sample count).
    """

    def __init__(self, devices, interval_s: float = SAMPLE_INTERVAL_S):
        self.devices = list(devices)
        self.interval_s = float(interval_s)
        self.stats: Dict[str, int] = {}
        self.samples = 0
        # sample() runs on both the sampler thread and the caller's
        # (start/stop probes); the merge must be atomic between them
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def sample(self) -> None:
        fresh = local_device_stats(self.devices)
        if fresh:
            with self._lock:
                self.samples += 1
                for k, v in fresh.items():
                    self.stats[k] = max(self.stats.get(k, 0), v)
                peak = self.stats.get("peak_bytes_in_use", 0)
            # live HBM watermark for the telemetry plane (scraped off the
            # replica by the heartbeat; the hbm_watermark alert rule reads
            # it). Published outside the merge lock; lazy import because
            # obs/__init__ binds memwatch before telemetry.
            from . import telemetry
            telemetry.publish("obs.peak_hbm_bytes", int(peak))

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.sample()

    def start(self) -> bool:
        """Probe; spawn the sampling thread only where stats exist."""
        self.sample()
        if not self.stats:
            return False
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="fakepta-hbm-sampler")
        self._thread.start()
        return True

    def stop(self) -> Dict[str, int]:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        self.sample()
        with self._lock:
            out = dict(self.stats)
            if self.samples:
                out["hbm_samples"] = self.samples
        return out


class PackedLedger:
    """Live packed-buffer accounting for one ``run()``'s chunk loop.

    The engine reports every fresh device allocation of a packed output
    buffer (:meth:`alloc`) and every donated-scratch recycle
    (:meth:`recycle`, with the post-dispatch ``is_deleted()`` verdict of
    the recycled buffer). On the pipelined path the donated ring bounds the
    number of distinct live packed buffers at ``ring_size``; a fresh-alloc
    count above that, or a recycled buffer that XLA did *not* consume
    (donation silently broken — the buffer would stay live beside its
    replacement), violates the bound and :meth:`check` raises.
    """

    def __init__(self, buffer_bytes: int, ring_size: int, pipelined: bool,
                 n_real_shards: int = 1):
        self.buffer_bytes = int(buffer_bytes)
        self.ring_size = int(ring_size)
        self.pipelined = bool(pipelined)
        self.n_real_shards = max(int(n_real_shards), 1)
        self.fresh_allocs = 0
        self.recycles = 0
        self.donation_misses = 0
        self.replacements = 0
        self.degraded = False

    def alloc(self) -> None:
        self.fresh_allocs += 1

    def alloc_replacement(self) -> None:
        """A retry replaced a donated-and-consumed scratch buffer: the old
        buffer is already deleted, so the live count is unchanged."""
        self.replacements += 1

    def disable(self) -> None:
        """Recovery degraded donation off mid-run (docs/RELIABILITY.md):
        the depth-bound claim is withdrawn for this run — :meth:`check`
        becomes a no-op — and the degradation is recorded by the engine
        (``faults.degradations`` counter + flight recorder), never
        silent."""
        self.degraded = True
        self.pipelined = False

    def recycle(self, donated_consumed: bool) -> None:
        self.recycles += 1
        if not donated_consumed:
            self.donation_misses += 1

    @property
    def live_buffers(self) -> int:
        """Distinct live packed device buffers (recycles reuse, never add)."""
        return self.fresh_allocs

    def check(self) -> None:
        """Assert the depth-packed-buffers bound with runtime evidence."""
        if not self.pipelined:
            return   # the serial loop makes no bounded-peak claim
        if self.fresh_allocs > self.ring_size or self.donation_misses:
            raise RuntimeError(
                f"pipeline depth bound violated: {self.fresh_allocs} packed "
                f"buffers allocated (bound {self.ring_size}), "
                f"{self.donation_misses} recycled scratch buffer(s) not "
                f"consumed by donation — peak HBM no longer holds "
                f"'depth' packed buffers (docs/PERFORMANCE.md); this is an "
                f"engine bug, please report it with the run's flightrec "
                f"dump")

    def memory_fields(self) -> Dict[str, int]:
        """The ledger's contribution to ``RunReport.memory``."""
        out = {
            "packed_buffer_bytes": self.buffer_bytes,
            "packed_buffers_live_peak": self.live_buffers,
        }
        if self.pipelined:
            out["packed_depth_bound_bytes"] = (
                self.ring_size * self.buffer_bytes)
        if self.degraded:
            out["packed_ring_degraded"] = 1
        return out

    def model_extra_bytes_per_device(self) -> int:
        """Per-device bytes of live packed buffers beyond the one the chunk
        program's static reservation already counts as its output."""
        extra = max(self.live_buffers - 1, 0)
        return extra * self.buffer_bytes // self.n_real_shards
