"""RunReport: the per-run telemetry artifact the ensemble engine emits.

Every ``EnsembleSimulator.run()`` returns one of these under the ``"report"``
key (and as ``sim.last_report``). It is a plain-data snapshot — meta, stage
spans, per-chunk wall times, compile/steady split, retrace count, one-time
XLA cost analysis and device-memory stats — with a stable JSON-lines
serialization (:meth:`save`/:meth:`load`, schema
:data:`~fakepta_tpu.obs.metrics.SCHEMA`) so BENCH_r*.json-style trajectories
stop being hand-reconstructed numbers and become diffable files
(``python -m fakepta_tpu.obs compare old.jsonl new.jsonl``).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .metrics import Collector, EventLog

# ---------------------------------------------------------------------------
# metric directions — single-sourced for `compare` (format_delta) and the
# trajectory gate (`obs gate`, .gate): which way is "worse"?
# ---------------------------------------------------------------------------

# exact names where bigger is better
HIGHER_IS_BETTER = {"real_per_s", "steady_real_per_s_per_chip",
                    "intensity_flop_per_byte",
                    # bench-row headline fields (the BENCH_r*.json schema):
                    # throughput value and its multiple of the v5e target
                    "value", "vs_baseline",
                    # the sampling lane's effective-sample count (its
                    # ess_per_s_per_chip / sample_steps_per_s_per_chip
                    # throughputs ride the _per_s_per_chip suffix, and
                    # rhat_max keeps the lower-is-better default: R-hat
                    # drifting up past the noise band IS a regression)
                    "ess_min",
                    # the serving layer (fakepta_tpu.serve, docs/SERVING
                    # .md): request throughput and the coalescing speedup
                    # over serial dispatch are the lane's whole point;
                    # coalesce_factor dropping means the scheduler stopped
                    # amortizing dispatches. serve_p50_ms/serve_p99_ms and
                    # pad_waste_frac keep the lower-is-better default.
                    "serve_qps_per_chip", "serve_serial_qps_per_chip",
                    "serve_speedup_x", "coalesce_factor",
                    # the serve FLEET (serve/fleet.py, docs/SERVING.md
                    # "Fleet"): aggregate request throughput and the
                    # scale-out multiple over one ServePool are the tier's
                    # whole point (fleet_solo_qps is the baseline side of
                    # that A/B — it dropping means the comparison got
                    # easier, which is itself a regression signal);
                    # fleet_p50_ms/fleet_p99_ms, fleet_failovers,
                    # fleet_spillovers, fleet_rejected, fleet_failed,
                    # fleet_lost_requests, fleet_replica_deaths and
                    # fleet_steady_compiles/fleet_retraces all keep the
                    # lower-is-better default; fleet_warm_hit_rate rides
                    # the _hit_rate suffix
                    "fleet_qps", "fleet_qps_per_chip", "fleet_speedup_x",
                    "fleet_solo_qps",
                    # the gateway tier (fakepta_tpu.gateway,
                    # docs/GATEWAY.md): device-seconds the content-
                    # addressed result store did not re-spend — the
                    # cache's whole point; gw_hit_rate rides the
                    # _hit_rate suffix and gw_p99_ms_under_quota /
                    # gw_cutover_ms keep the lower-is-better default
                    "gw_device_s_saved",
                    # the autotuner (fakepta_tpu.tune, docs/TUNING.md):
                    # tuned-vs-hand-set throughput multiple — dropping
                    # below its band means the tuner stopped finding (or
                    # keeping) wins; tune_probe_s keeps the lower-is-
                    # better default (probe time is pure overhead) and
                    # the `tuned` flag itself is exempt (a run-shape fact)
                    "tuned_speedup_x", "tuned_real_per_s_per_chip",
                    # the streaming-ingestion lane (fakepta_tpu.stream,
                    # docs/STREAMING.md): the incremental-append-vs-full-
                    # restage A/B multiple is the lane's whole point —
                    # append_latency_ms keeps the lower-is-better default,
                    # and stream_recompiles keeps it too (any growth past
                    # the zero history is the bucket ladder regressing)
                    "append_speedup_x",
                    # the factorized free-spectrum lane (sample/
                    # factorized.py, stream/refresh.py FactorizedRefresher,
                    # docs/SAMPLING.md): the factorized-vs-joint ESS/s
                    # multiple and the incremental-vs-full refresh multiple
                    # are the lane's whole point (fs_ess_per_s_per_chip
                    # rides the _per_s_per_chip suffix; fs_refresh_ms /
                    # fs_oracle_max_err / fs_recompiles keep the lower-is-
                    # better default)
                    "fs_speedup_x", "fs_refresh_speedup_x"}

# suffix rules cover the detect lane's per-ORF metric names
# (os_<orf>_significance_sigma, os_<orf>_detection_rate), the infer lane's
# recovery metrics (lnlike_map_hit_rate; its lnlike_map_l2_mean distance and
# *_bytes_per_chunk / model_bytes_per_chunk costs keep the lower-is-better
# default, so a byte-per-chunk growth IS a regression), any *_per_s_per_chip
# / evals throughput metric, the roofline intensity, and the bench rows'
# *_reduction_x byte-savings factors
HIGHER_SUFFIXES = ("_per_s_per_chip", "_significance_sigma",
                   "_detection_rate", "_hit_rate", "_reduction_x")

# run-shape facts and distribution-scale diagnostics, not performance or
# quality metrics — moving is information, not a regression (the infer
# lane's lnL scale and grid size land here: a model change legitimately
# moves absolute lnL without being better or worse). The pipeline's overlap
# timings (pipeline_stall_s / ckpt_wait_s) stay REGRESSABLE and
# lower-is-better — the default direction — but the depth itself is a
# run-shape fact, as are the memwatch accounting facts (buffer size, the
# depth bound itself) whose *violation* is a runtime error, not a delta.
EXEMPT_METRICS = {"nreal", "chunks", "pipeline_depth", "config",
                  "hbm_samples", "packed_buffer_bytes",
                  "packed_buffers_live_peak", "packed_depth_bound_bytes",
                  # sampler kernel-health diagnostics: acceptance/swap rates
                  # are tuning targets with a non-monotonic optimum (~0.65-
                  # 0.9 for HMC), so neither direction is "worse"; the
                  # regression-bearing sampler metrics are ess_min /
                  # ess_per_s_per_chip / sample_steps_per_s_per_chip
                  # (higher-better) and rhat_max / divergences /
                  # nonfinite_lnl (lower-better defaults)
                  "accept_rate", "swap_rate", "n_kept",
                  # serve load-shape facts: how deep the queue got and how
                  # many requests/realizations the window saw are traffic
                  # description, not performance (the regression-bearing
                  # serve metrics are serve_qps_per_chip / serve_p50_ms /
                  # serve_p99_ms / coalesce_factor / pad_waste_frac);
                  # serve_retraces and serve_steady_compiles keep the
                  # lower-is-better default — any growth past the zero
                  # history IS the warm pool regressing
                  "queue_depth", "serve_requests", "serve_dispatches",
                  "serve_realizations", "serve_kind", "serve_verified",
                  "serve_warm_s",
                  # fleet load-shape facts (serve/fleet.py): replica
                  # counts, traffic description, which replica the chaos
                  # lane killed, verification tallies, and the baseline
                  # pool's p50 (a reference condition, not a serve SLO —
                  # the fleet's own p50/p99 stay regression-bearing)
                  "fleet_replicas", "fleet_replicas_alive",
                  "fleet_requests", "fleet_kind", "fleet_transport",
                  "fleet_killed_replica", "fleet_verified",
                  "fleet_verified_failover", "fleet_solo_p50_ms",
                  # fleet lifecycle shape facts (serve/health.py,
                  # serve/autoscale.py, the config15 elastic chaos lane):
                  # membership churn, probe volume, which replica the lane
                  # wedged/joined and what state the breaker reached are
                  # scenario description — the scripted chaos MAKES them
                  # nonzero. The regression-bearing lifecycle metrics keep
                  # the lower-is-better default: fleet_heartbeat_misses /
                  # fleet_breaker_opens (unscripted misses are a fleet
                  # degrading), fleet_timeouts, fleet_lost_requests, and
                  # fleet_join_steady_compiles (any growth past zero is
                  # the warm-join contract breaking)
                  "fleet_joins", "fleet_drains", "fleet_probes",
                  "fleet_breaker_closes", "fleet_breakered",
                  "fleet_wedged", "fleet_wedge_state",
                  "fleet_wedged_replica", "fleet_joined_replica",
                  "scale_events",
                  # chaos-lane shape fact (benchmarks/suite.py config 12):
                  # how many injected faults the run recovered — the
                  # regression-bearing metrics are the recovery counters
                  # themselves (faults_retries / faults_degradations /
                  # faults_rollbacks, lower-better defaults) and
                  # fault_recovery_overhead_frac (lower-better default)
                  "faults_recovered", "packed_ring_degraded",
                  # autotuner run-shape facts: whether tuned knobs rode
                  # the run / how many probes the search issued are
                  # configuration description, not performance (the
                  # regression-bearing tune metrics are tuned_speedup_x,
                  # tuned_real_per_s_per_chip — higher-better above — and
                  # tune_probe_s, lower-better default)
                  "tuned", "tune_probes",
                  # streaming-lane shape facts (fakepta_tpu.stream): how
                  # many TOAs/appends the window ingested and how often the
                  # bucket ladder legitimately stepped up are traffic
                  # description (the regression-bearing stream metrics are
                  # append_speedup_x — higher-better above — and
                  # append_latency_ms / stream_recompiles, lower-better
                  # defaults)
                  "stream_appends", "stream_toas", "stream_rebuckets",
                  # scenario golden stream lane: expected first-sighting
                  # bucket-rung compiles — a deterministic function of
                  # the cadence's block-size mix, not a health signal
                  # (the zero-expected canary stays stream_recompiles)
                  "stream_compiles",
                  # telemetry-plane shape facts (docs/OBSERVABILITY.md):
                  # scrape volume rides the heartbeat cadence and trace
                  # flow counts describe the traffic, not its health (the
                  # regression-bearing telemetry metrics keep the lower-
                  # is-better default: fleet_scrape_errors, fleet_alerts,
                  # telemetry_overhead_frac)
                  "fleet_scrapes", "trace_flows",
                  # gateway-lane shape facts (fakepta_tpu.gateway,
                  # docs/GATEWAY.md, the config16 Zipf tenant mix):
                  # traffic volume, tenant count, bit-verification tallies,
                  # throttle counts (the scripted overload MAKES the hot
                  # tenant throttle — per-tenant 429s are the isolation
                  # mechanism working, not a regression) and coalesce
                  # counts (race-timing dependent). The regression-bearing
                  # gateway metrics are gw_hit_rate (higher, via the
                  # _hit_rate suffix), gw_device_s_saved (higher above)
                  # and gw_p99_ms_under_quota / gw_cutover_ms
                  # (lower-better below)
                  "gw_requests", "gw_tenants", "gw_verified",
                  "gw_throttles", "gw_coalesced",
                  # factorized free-spectrum shape facts: how many lanes
                  # the plan produced and how many bins/lanes an append
                  # actually touched are decomposition/scenario
                  # description — the scripted append MAKES them nonzero
                  # (the regression-bearing factorized metrics are
                  # fs_speedup_x / fs_refresh_speedup_x /
                  # fs_ess_per_s_per_chip — higher-better — and
                  # fs_refresh_ms / fs_full_refresh_ms / fs_oracle_max_err
                  # / fs_recompiles / fs_wall_s_critical, lower-better)
                  "fs_lane_count", "fs_lanes_touched", "fs_bins_touched"}
EXEMPT_SUFFIXES = ("_amp2_mean", "_sigma_empirical", "_sigma_analytic",
                   "_null_q95", "_p_value_median", "_lnl_max_mean",
                   "_grid_k")

# non-numeric row-identity fields of the BENCH schema (bench.py docstring):
# strings/flags that label a row rather than measure it — `compare` skips
# non-numerics anyway; this table exists so the direction contract below
# is total
ROW_IDENTITY = {"metric", "unit", "platform", "fallback",
                # scenario golden rows (fakepta_tpu.scenarios): the
                # registered scenario name is grouping identity exactly
                # like platform — `obs gate` bands a golden row only
                # against same-scenario, same-platform history
                "scenario"}

# exact names where smaller is better. Functionally this is the DEFAULT
# direction — metric_higher_is_better() returns False for any name not in
# the tables above — so this set changes no behavior. It exists as the
# explicit other half of the direction contract: every metric key in the
# bench.py schema docstring must appear in exactly one of HIGHER_IS_BETTER
# / LOWER_IS_BETTER / EXEMPT_METRICS / ROW_IDENTITY (or match a suffix
# rule), and the tier-1 completeness test enforces it — a new bench key
# can no longer pick up a direction silently.
LOWER_IS_BETTER = {"compile_s", "retraces", "cost_bytes_per_chunk",
                   "cost_flops_per_chunk", "os_bytes_per_chunk",
                   "lnlike_bytes_per_chunk", "pipeline_stall_s",
                   "ckpt_wait_s", "model_bytes_per_chunk",
                   "cost_bytes_per_chunk_fused",
                   "cost_bytes_per_chunk_fused_bf16",
                   "model_bytes_per_chunk_fused",
                   "model_bytes_per_chunk_fused_bf16",
                   "rhat_max", "serve_p50_ms", "serve_p99_ms",
                   "pad_waste_frac", "serve_retraces",
                   "serve_steady_compiles", "fleet_p50_ms", "fleet_p99_ms",
                   "fleet_failovers", "fleet_lost_requests",
                   "fleet_steady_compiles", "fleet_heartbeat_misses",
                   "fleet_breaker_opens", "fleet_timeouts",
                   "fleet_join_steady_compiles", "append_latency_ms",
                   "restage_ms", "stream_recompiles", "faults_retries",
                   "faults_degradations", "faults_rollbacks",
                   "tune_probe_s", "peak_hbm_bytes",
                   # gateway lane (docs/GATEWAY.md): admitted-request p99
                   # while the hot tenant rides its fair-share quota, and
                   # the fence-to-swap cost of a managed migration cutover
                   "gw_p99_ms_under_quota", "gw_cutover_ms",
                   # telemetry plane (docs/OBSERVABILITY.md): failed
                   # scrapes, fired alert rules, and the scrape-on vs
                   # scrape-off qps cost are all degradations
                   "fleet_scrape_errors", "fleet_alerts",
                   "telemetry_overhead_frac",
                   # scenario golden-run lane (fakepta_tpu.scenarios,
                   # docs/SCENARIOS.md): the scenario's ensemble HBM
                   # watermark and the cadence-driven append tail are
                   # degradations when they grow (the higher-better
                   # golden metrics — scn_ess_per_s_per_chip,
                   # scn_real_per_s_per_chip — ride the
                   # _per_s_per_chip suffix rule)
                   "scn_peak_hbm_bytes", "scn_append_p99_ms",
                   # factorized free-spectrum lane (docs/SAMPLING.md):
                   # the f64 additivity defect is the exactness canary
                   # (config 18 refuses rows past its gate), steady lane
                   # recompiles must stay at zero, and the refresh
                   # latencies/wall times are costs
                   "fs_oracle_max_err", "fs_recompiles", "fs_refresh_ms",
                   "fs_full_refresh_ms", "fs_wall_s_total",
                   "fs_wall_s_critical"}


def metric_higher_is_better(k: str) -> bool:
    """True when a DROP in metric ``k`` is the regression direction."""
    return k in HIGHER_IS_BETTER or k.endswith(HIGHER_SUFFIXES)


def metric_exempt(k: str) -> bool:
    """True when metric ``k`` is informational (never a regression)."""
    return k in EXEMPT_METRICS or k.endswith(EXEMPT_SUFFIXES)


@dataclass
class RunReport:
    """Structured telemetry for one ``run()`` call."""

    meta: Dict = field(default_factory=dict)      # nreal/chunk/platform/mesh..
    spans: List[str] = field(default_factory=list)
    chunks: List[dict] = field(default_factory=list)   # {idx, wall_s, synced}
    counters: Dict[str, float] = field(default_factory=dict)
    gauges: Dict[str, float] = field(default_factory=dict)
    timings: Dict[str, List[float]] = field(default_factory=dict)
    retraces: int = 0
    compile_s: float = 0.0
    total_s: float = 0.0
    cost: Dict[str, float] = field(default_factory=dict)
    memory: Dict[str, float] = field(default_factory=dict)
    # run-relative span records from both the dispatch and writer threads
    # ({name, t0, dur, tid, chunk, ...} — seconds; dur None = instant);
    # the raw material `obs trace` turns into a Chrome/Perfetto timeline
    timeline: List[dict] = field(default_factory=list)

    # -- derived -----------------------------------------------------------
    @property
    def nchunks(self) -> int:
        return len(self.chunks)

    @property
    def first_chunk_s(self) -> float:
        return self.chunks[0]["wall_s"] if self.chunks else 0.0

    @property
    def steady_s(self) -> float:
        """Wall time excluding the first (trace+compile-bearing) chunk."""
        return max(self.total_s - self.first_chunk_s, 0.0)

    def real_per_s(self) -> float:
        n = self.meta.get("nreal", 0)
        return n / self.total_s if self.total_s > 0 else 0.0

    def steady_real_per_s(self) -> float:
        """Steady-state realizations/s. On a cold run the first chunk bears
        trace+compile, so it is excluded (count and wall) when the run has
        more than one chunk, or its compile time subtracted when it has only
        one. A warm run (``compile_s == 0``) is steady throughout — excluding
        its first chunk would drop realizations without dropping time."""
        n = self.meta.get("nreal", 0)
        chunk = self.meta.get("chunk", n)
        if self.compile_s <= 0:
            return self.real_per_s()
        if self.nchunks > 1 and self.steady_s > 0:
            return (n - min(chunk, n)) / self.steady_s
        denom = self.total_s - self.compile_s
        return n / denom if denom > 0 else 0.0

    def steady_real_per_s_per_chip(self) -> float:
        return self.steady_real_per_s() / max(self.meta.get("n_devices", 1), 1)

    # -- summary metrics (the flat table `compare` diffs) ------------------
    def summary(self) -> Dict[str, float]:
        m = {
            "nreal": self.meta.get("nreal", 0),
            "chunks": self.nchunks,
            "retraces": self.retraces,
            "compile_s": round(self.compile_s, 6),
            "total_s": round(self.total_s, 6),
            "first_chunk_s": round(self.first_chunk_s, 6),
            "real_per_s": round(self.real_per_s(), 3),
            "steady_real_per_s_per_chip":
                round(self.steady_real_per_s_per_chip(), 3),
        }
        if self.cost.get("bytes_per_chunk"):
            m["cost_bytes_per_chunk"] = self.cost["bytes_per_chunk"]
        if self.cost.get("flops_per_chunk"):
            m["cost_flops_per_chunk"] = self.cost["flops_per_chunk"]
        if self.cost.get("model_bytes_per_chunk"):
            # the analytic HBM-traffic model beside the measured number
            # (ops/megakernel.py chunk_bytes_model — the roofline source of
            # truth on platforms whose cost analysis can't see TPU fusion);
            # lower-is-better, like every *_bytes_per_chunk metric
            m["model_bytes_per_chunk"] = self.cost["model_bytes_per_chunk"]
        if self.cost.get("bytes_per_chunk") and \
                self.cost.get("flops_per_chunk"):
            # arithmetic intensity of the chunk program — the roofline
            # x-coordinate; HIGHER is better (the whole point of the fused
            # megakernel is pushing it toward the ridge), and `compare`
            # treats it so
            m["intensity_flop_per_byte"] = round(
                self.cost["flops_per_chunk"] / self.cost["bytes_per_chunk"],
                3)
        if self.memory.get("peak_bytes_in_use"):
            m["peak_bytes_in_use"] = self.memory["peak_bytes_in_use"]
        if self.memory.get("peak_hbm_bytes"):
            # the HBM watermark (obs.memwatch): allocator peak max-aggregated
            # over local devices and over the low-rate sampler's samples
            # where the backend exposes stats, else the packed-buffer model;
            # lower-is-better in `compare` (the default direction), and the
            # bench rows carry it (bench.py docstring schema)
            m["peak_hbm_bytes"] = self.memory["peak_hbm_bytes"]
        if self.meta.get("pipeline_depth") is not None:
            # the async chunk pipeline's overlap figures (docs/PERFORMANCE
            # .md): stall_s is host work the dispatch actually waited on,
            # ckpt_wait_s the checkpoint appends (overlapped on the writer
            # thread when pipelined, inside the chunk wall when serial).
            # Both are lower-is-better in `compare` — the default direction
            m["pipeline_depth"] = int(self.meta["pipeline_depth"])
            m["pipeline_stall_s"] = round(
                sum(c.get("stall_s", 0.0) for c in self.chunks), 6)
            m["ckpt_wait_s"] = round(
                sum(c.get("ckpt_wait_s", 0.0) for c in self.chunks), 6)
        if self.meta.get("os"):
            # an OS-lane run: the same steady rate and chunk cost, under the
            # names bench.py / benchmarks rows carry for the detection lane —
            # `compare --fail-on-regression` then gates the OS path too
            m["os_real_per_s_per_chip"] = round(
                self.steady_real_per_s_per_chip(), 3)
            if self.cost.get("bytes_per_chunk"):
                m["os_bytes_per_chunk"] = self.cost["bytes_per_chunk"]
        if self.meta.get("tuned"):
            # autotuned knobs rode this run (fakepta_tpu.tune): exempt
            # flag so `compare` shows the attribution without treating a
            # tuned/hand-set switch as a regression; the knob detail
            # stays in meta["tuned"]["knobs"]
            m["tuned"] = 1
        if self.meta.get("lnlike"):
            # a likelihood-lane run (fakepta_tpu.infer): the steady rate
            # times the grid size is the evaluation throughput bench.py /
            # benchmarks rows record; chunk cost under the lane's name so
            # `compare --fail-on-regression` gates the inference path too
            k = int(self.meta["lnlike"].get("k", 1))
            m["lnlike_evals_per_s_per_chip"] = round(
                self.steady_real_per_s_per_chip() * k, 3)
            if self.cost.get("bytes_per_chunk"):
                m["lnlike_bytes_per_chunk"] = self.cost["bytes_per_chunk"]
        # host-attached metrics (e.g. detect.DetectionRun's significance /
        # detection-rate summary) round-trip through meta so a loaded
        # artifact diffs them like any engine metric
        extra = self.meta.get("extra_metrics")
        if isinstance(extra, dict):
            m.update(extra)
        return m

    # -- construction ------------------------------------------------------
    @classmethod
    def from_collector(cls, collector: Collector, meta: dict,
                       **kwargs) -> "RunReport":
        rep = cls(meta=dict(meta), spans=list(collector.spans),
                  counters=dict(collector.counters),
                  gauges=dict(collector.gauges),
                  timings={k: list(v) for k, v in collector.timings.items()},
                  **kwargs)
        # compile time is authoritative from the jax.monitoring bridge when
        # the events fired; sub-jits contribute several events, so sum them
        rep.compile_s = sum(rep.timings.get("jax.backend_compile_s", []))
        return rep

    # -- serialization -----------------------------------------------------
    def to_json(self) -> dict:
        return {
            "meta": self.meta, "spans": self.spans, "chunks": self.chunks,
            "counters": self.counters, "gauges": self.gauges,
            "timings": self.timings, "timeline": self.timeline,
            "retraces": self.retraces,
            "compile_s": self.compile_s, "total_s": self.total_s,
            "cost": self.cost, "memory": self.memory,
            "summary": self.summary(),
        }

    def save(self, path) -> str:
        """Write the JSON-lines artifact (schema-framed; see module doc)."""
        log = EventLog(meta=self.meta)
        for name in self.spans:
            log.append("span", name=name)
        for c in self.chunks:
            log.append("chunk", **c)
        for ev in sorted(self.timeline, key=lambda e: e.get("t0", 0.0)):
            log.append("tl", **ev)
        for name, value in sorted(self.counters.items()):
            log.append("counter", name=name, value=value)
        for name, value in sorted(self.gauges.items()):
            log.append("gauge", name=name, value=value)
        for name, values in sorted(self.timings.items()):
            log.append("timing", name=name, values=values)
        log.append("report", retraces=self.retraces,
                   compile_s=self.compile_s, total_s=self.total_s,
                   cost=self.cost, memory=self.memory)
        return log.save(path, summary=self.summary())

    @classmethod
    def load(cls, path) -> "RunReport":
        log = EventLog.load(path)
        rep = cls(meta=log.meta)
        for line in log.lines:
            kind = line.get("kind")
            if kind == "span":
                rep.spans.append(line["name"])
            elif kind == "chunk":
                rep.chunks.append(
                    {k: v for k, v in line.items() if k != "kind"})
            elif kind == "counter":
                rep.counters[line["name"]] = line["value"]
            elif kind == "gauge":
                rep.gauges[line["name"]] = line["value"]
            elif kind == "timing":
                rep.timings[line["name"]] = list(line["values"])
            elif kind == "tl":
                rep.timeline.append(
                    {k: v for k, v in line.items() if k != "kind"})
            elif kind == "report":
                rep.retraces = int(line.get("retraces", 0))
                rep.compile_s = float(line.get("compile_s", 0.0))
                rep.total_s = float(line.get("total_s", 0.0))
                rep.cost = dict(line.get("cost", {}))
                rep.memory = dict(line.get("memory", {}))
        return rep

    def __repr__(self) -> str:   # compact, log-friendly
        return (f"RunReport(nreal={self.meta.get('nreal')}, "
                f"chunks={self.nchunks}, retraces={self.retraces}, "
                f"compile_s={self.compile_s:.3f}, total_s={self.total_s:.3f})")


def format_summary(rep: RunReport) -> str:
    """Human-readable one-report table (the ``summarize`` CLI body)."""
    rows = [("metric", "value")]
    for k, v in rep.summary().items():
        rows.append((k, f"{v:g}" if isinstance(v, float) else str(v)))
    rows.append(("spans", ",".join(rep.spans) or "-"))
    w = max(len(r[0]) for r in rows)
    return "\n".join(f"{k:<{w}}  {v}" for k, v in rows)


def format_delta(a: RunReport, b: RunReport,
                 rel_threshold: float = 0.10) -> tuple:
    """Per-metric delta table between two reports.

    Returns ``(text, regressions)`` where regressions is the list of metric
    names that moved the wrong way beyond ``rel_threshold`` (throughput down,
    retraces/compile/cost up).
    """
    ma, mb = a.summary(), b.summary()
    keys = sorted(set(ma) | set(mb))
    # direction/exemption rules are the module-level tables above, shared
    # with the trajectory gate (`obs gate`) so the two can never disagree
    # about which way is "worse"
    lines = [f"{'metric':<28} {'a':>14} {'b':>14} {'delta':>12}"]
    regressions = []
    def _num(v):
        return (float(v) if isinstance(v, (int, float))
                and not isinstance(v, bool) else None)

    for k in keys:
        va, vb = ma.get(k), mb.get(k)
        if _num(va) is None or _num(vb) is None:
            # missing on one side, or a non-numeric (schema-partial) value
            # — informational row, never a TypeError traceback
            lines.append(f"{k:<28} {va if va is not None else '-':>14} "
                         f"{vb if vb is not None else '-':>14} {'-':>12}")
            continue
        delta = vb - va
        rel = delta / abs(va) if va else (1.0 if delta else 0.0)
        flag = ""
        if not metric_exempt(k) and abs(rel) > rel_threshold:
            worse = rel < 0 if metric_higher_is_better(k) else rel > 0
            if worse:
                flag = "  << REGRESSION"
                regressions.append(k)
        lines.append(f"{k:<28} {va:>14g} {vb:>14g} {rel:>+11.1%}{flag}")
    return "\n".join(lines), regressions
