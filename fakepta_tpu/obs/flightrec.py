"""Crash flight recorder: an always-on bounded ring of recent obs events.

A killed or crashed run used to leave nothing but a stack trace — the
RunReport is assembled only *after* a successful run, so the one case where
telemetry matters most (a mid-pipeline drain failure, an OOM, an operator
kill) produced no artifact at all. This module keeps a process-wide bounded
ring buffer of recent observability events (:data:`RING_SIZE`, oldest
dropped first) that is **always on**: :func:`note` costs one
``deque.append`` of a small tuple whether or not a collector is installed,
so the engine records into it unconditionally (``obs.event(...)`` mirrors
here too — any event a collector would see is also in the ring).

On an engine or pipeline exception, :meth:`EnsembleSimulator.run` dumps the
ring plus the run's identity — spec hash, mesh/meta, the per-chunk records
completed so far — to ``<ckpt_dir>/flightrec-<ts>-p<process>.json`` (next to
the checkpoint when one was requested, else under
``$FAKEPTA_TPU_FLIGHTREC_DIR`` when set). The dump is a schema-framed
``fakepta_tpu.obs/1`` JSON-lines file, so it round-trips through
``python -m fakepta_tpu.obs summarize`` like any RunReport artifact:
the crash is diagnosable from the run's own directory.

Clock reads here are ``time.perf_counter`` directly rather than
``obs.timing.now`` to keep this module import-cycle-free (timing imports
metrics, metrics mirrors events here); the module is allowlisted by the
``timing-discipline`` rule (analysis.policy.TIMING_MODULES).
"""

from __future__ import annotations

import collections
import hashlib
import json
import os
import threading
import time
from pathlib import Path
from typing import List, Optional

# ring capacity: large enough to hold the tail of a long run (every chunk
# contributes a handful of events), small enough that the ring is noise in
# host memory and a dump stays a quick glance
RING_SIZE = 256

# opt-in dump directory for runs without a checkpoint path
DUMP_DIR_ENV = "FAKEPTA_TPU_FLIGHTREC_DIR"

_ring: "collections.deque" = collections.deque(maxlen=RING_SIZE)
# dumps can race (engine thread + a writer-thread failure unwinding two
# stacks); serialize them so two dumps never interleave into one file
_dump_lock = threading.Lock()


def note(name: str, **attrs) -> None:
    """Append one event to the ring (always on; never raises).

    The stored tuple is ``(t_monotonic_s, name, attrs-or-None)``;
    ``deque.append`` is atomic under the GIL, so the engine thread and the
    pipeline's writer thread record concurrently without a lock.
    """
    _ring.append((time.perf_counter(), name, attrs or None))


def snapshot() -> List[dict]:
    """The ring's current contents, oldest first, as plain dicts."""
    out = []
    for t, name, attrs in list(_ring):
        ev = {"t_mono_s": round(t, 6), "name": name}
        if attrs:
            ev["attrs"] = attrs
        out.append(ev)
    return out


def clear() -> None:
    """Empty the ring (tests; a new process starts empty anyway)."""
    _ring.clear()


def spec_hash(meta: dict) -> str:
    """Stable short hash of a run's identity (meta minus volatile fields).

    Two runs of the same spec — same ensemble shape, lanes, mesh, precision
    — hash identically regardless of nreal/seed, so crash dumps group by
    configuration across a campaign.
    """
    volatile = {"nreal", "seed", "extra_metrics"}
    stable = {k: v for k, v in sorted(meta.items()) if k not in volatile}
    blob = json.dumps(stable, sort_keys=True, default=str)
    return hashlib.sha1(blob.encode()).hexdigest()[:12]


def dump_dir(checkpoint=None) -> Optional[Path]:
    """Where a dump should land: the checkpoint's directory when the run has
    one, else ``$FAKEPTA_TPU_FLIGHTREC_DIR``, else None (no dump)."""
    if checkpoint is not None:
        return Path(checkpoint).resolve().parent
    env = os.environ.get(DUMP_DIR_ENV)
    return Path(env) if env else None


def dump(directory, meta: dict, chunks=None, error: str = "",
         process_index: int = 0) -> Optional[str]:
    """Write the flight-recorder artifact; returns its path (None on any
    failure — a dump must never mask the exception being handled).

    The file is a ``fakepta_tpu.obs/1`` JSON-lines EventLog: header (meta +
    spec hash + crash context), the per-chunk records completed so far, the
    ring's events, and a summary line — loadable by ``RunReport.load`` and
    printable by ``python -m fakepta_tpu.obs summarize``.
    """
    try:
        from .metrics import EventLog

        directory = Path(directory)
        directory.mkdir(parents=True, exist_ok=True)
        ts = time.strftime("%Y%m%d-%H%M%S")
        path = directory / f"flightrec-{ts}-p{process_index:03d}.json"
        chunks = list(chunks or [])
        head_meta = dict(meta)
        head_meta.update({
            "flightrec": True,
            "spec_hash": spec_hash(meta),
            "crash_time": time.strftime("%Y-%m-%dT%H:%M:%S"),
            "error": error[:2000],
        })
        log = EventLog(meta=head_meta)
        for c in chunks:
            log.append("chunk", **c)
        for ev in snapshot():
            log.append("event", **ev)
        summary = {
            "chunks_completed": len(chunks),
            "events_recorded": len(_ring),
            "nreal": int(meta.get("nreal", 0)),
        }
        with _dump_lock:
            log.save(path, summary=summary)
        return str(path)
    except Exception:                                    # pragma: no cover
        return None
