"""PulsarBatch: the padded, masked, device-resident representation of a PTA.

The reference iterates Python lists of ``Pulsar`` objects everywhere; the scale
axes (npsr x n_toa x n_realizations) are all Python loops (SURVEY.md §5). The batch
engine flips the layout: every per-pulsar quantity becomes one padded ``(npsr,
max_toa)`` array plus a validity mask, hyper-parameters become dense arrays, and
the whole structure is a pytree that moves through jit/vmap/shard_map untouched.

Precision design: absolute TOAs (1e8-1e9 s) cannot live in float32, so the batch
stores *normalized* times — ``t/Tspan_pulsar`` for per-pulsar noises and
``t/Tspan_array`` (common origin) for cross-pulsar signals. Fourier phases are then
``2 pi n t_norm`` with ``n <= ~100``: float32-exact to ~1e-5 rad. The standard GP
grid ``f_n = n/Tspan`` makes every bin width ``df = 1/Tspan``, a scalar per pulsar.

Cited reference behavior being batched: per-pulsar Fourier injection
(``fake_pta.py:357-387``), white noise (``fake_pta.py:201-230``), the GWB draw
(``correlated_noises.py:111-160``).
"""

from __future__ import annotations

import dataclasses
import warnings
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .utils.masks import stack_ragged

# the GP bands from_pulsars packs, with their canonical chromatic indices; the
# unbatched-signal warning derives from the same tuple so they cannot drift
_BATCHED_GPS = (("red_noise", 0.0), ("dm_gp", 2.0), ("chrom_gp", 4.0))


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class PulsarBatch:
    """Device-ready PTA state. All arrays padded to (npsr, max_toa)."""

    # data fields (pytree leaves)
    t_own: jax.Array        # (P, T) toas normalized by each pulsar's Tspan
    t_common: jax.Array     # (P, T) toas normalized by the array Tspan, common origin
    mask: jax.Array         # (P, T) bool validity
    freqs: jax.Array        # (P, T) observing frequency [MHz]
    sigma2: jax.Array       # (P, T) white-noise variance per TOA [s^2]
    pos: jax.Array          # (P, 3) sky unit vectors
    red_psd: jax.Array      # (P, NR) red-noise PSD on the per-pulsar grid (0 = off)
    dm_psd: jax.Array       # (P, ND) DM-noise PSD (0 = off)
    chrom_psd: jax.Array    # (P, NC) chromatic (scattering, idx=4) PSD (0 = off)
    epoch_idx: jax.Array    # (P, T) int32 per-TOA epoch id (for ECORR)
    ecorr_amp: jax.Array    # (P, T) per-TOA ECORR amplitude [s] (0 = off)
    sys_psd: jax.Array      # (P, B, NS) per-backend system-noise PSD (0 = off)
    sys_mask: jax.Array     # (P, B, T) TOA membership of each system band
    df_own: jax.Array       # (P,) per-pulsar bin width 1/Tspan_p [Hz]
    tspan_common: jax.Array # () array Tspan [s]

    @property
    def npsr(self) -> int:
        return self.t_own.shape[0]

    @property
    def max_toa(self) -> int:
        return self.t_own.shape[1]

    @classmethod
    def from_pulsars(cls, psrs: Sequence, n_red: int = 30, n_dm: int = 100,
                     n_chrom: int = 30, n_sys: int = 30, ecorr: bool = False,
                     ecorr_dt: float = 1.0, dtype=jnp.float32) -> "PulsarBatch":
        """Pack a list of (facade or ENTERPRISE-style) pulsars into one batch.

        PSDs (red / DM / chromatic) are taken from each pulsar's injected
        ``signal_model`` when present (padded with zeros up to the batch bin
        counts), else zero (signal off). White-noise variances resolve from the
        noisedict per backend, exactly as ``add_white_noise`` does
        (``fake_pta.py:214-217``).

        ``ecorr=True`` additionally resolves per-backend ``log10_ecorr`` values
        and quantizes TOAs into epochs (``ecorr_dt`` days). The batch sampler
        exploits the block structure sigma^2 I + c^2 11^T exactly: one shared
        normal per epoch, no per-block Cholesky (vs the reference's dense MVN
        per block, ``fake_pta.py:219-228``).

        Per-backend system noises (``signal_model`` keys
        ``'<backend>_system_noise_<backend>'``) become masked GP bands:
        ``sys_psd`` holds each band's PSD and ``sys_mask`` its backend's TOA
        membership, padded to the largest band count in the array.
        """
        from .ops.white import quantise_epochs

        toas_list = [np.asarray(p.toas, dtype=np.float64) for p in psrs]
        tmin = min(t.min() for t in toas_list)
        tmax = max(t.max() for t in toas_list)
        tspan_common = tmax - tmin

        toas_pad, mask = stack_ragged(toas_list)
        npsr, T = toas_pad.shape

        t_own = np.zeros((npsr, T))
        freqs = np.zeros((npsr, T))
        sigma2 = np.zeros((npsr, T))
        red_psd = np.zeros((npsr, n_red))
        dm_psd = np.zeros((npsr, n_dm))
        chrom_psd = np.zeros((npsr, n_chrom))
        epoch_idx = np.zeros((npsr, T), dtype=np.int32)
        ecorr_amp = np.zeros((npsr, T))
        sys_bands = []              # per pulsar: list of (mask (T,), psd (NS,))
        df_own = np.zeros(npsr)
        pos = np.stack([np.asarray(p.pos, dtype=np.float64) for p in psrs])

        for i, p in enumerate(psrs):
            n = len(toas_list[i])
            tspan = toas_list[i].max() - toas_list[i].min()
            df_own[i] = 1.0 / tspan
            t_own[i, :n] = (toas_list[i] - toas_list[i].min()) / tspan
            freqs[i, :n] = np.asarray(p.freqs, dtype=np.float64)[:n]
            freqs[i, n:] = 1400.0
            # white-noise variance from the noisedict, per backend
            efac = np.ones(n)
            equad = np.full(n, -np.inf)
            for backend in np.unique(np.asarray(p.backend_flags)):
                sel = np.asarray(p.backend_flags) == backend
                efac[sel] = p.noisedict.get(f"{p.name}_{backend}_efac", 1.0)
                equad[sel] = p.noisedict.get(f"{p.name}_{backend}_log10_tnequad", -8.0)
            sigma2[i, :n] = (efac**2 * np.asarray(p.toaerrs[:n]) ** 2
                             + 10.0 ** (2.0 * equad))
            if ecorr:
                flags = np.asarray(p.backend_flags)[:n]
                idx, _, ep_counts = quantise_epochs(
                    toas_list[i] - toas_list[i].min(), flags,
                    dt=ecorr_dt * 86400.0)
                epoch_idx[i, :n] = idx
                for backend in np.unique(flags):
                    sel = flags == backend
                    ecorr_amp[i, :n][sel] = 10.0 ** p.noisedict.get(
                        f"{p.name}_{backend}_log10_ecorr", -np.inf)
                # epochs with a single TOA get plain white noise, matching the
                # facade and the reference (fake_pta.py:223-224)
                ecorr_amp[i, :n][ep_counts[idx] < 2] = 0.0
            def check_grid(key, entry):
                # every batched band lives on the standard n/Tspan_pulsar grid
                # (df_own scaling assumes it); a custom f_psd must not be
                # silently re-gridded
                f = np.asarray(entry.get("f", []))
                expect = np.arange(1, len(f) + 1) / tspan
                if f.size and not np.allclose(f, expect, rtol=1e-6):
                    raise ValueError(
                        f"{p.name}.{key} uses a custom frequency grid; the "
                        f"batch engine requires the standard n/Tspan grid")

            # grid mismatches raise above; silently dropping *signals* would be
            # inconsistent strictness, so anything this packer does not batch is
            # warned about explicitly (ADVICE r1 #3)
            known = {name for name, _ in _BATCHED_GPS}
            unhandled = [key for key in getattr(p, "signal_model", {})
                         if key not in known and "system_noise_" not in key]
            if unhandled:
                warnings.warn(
                    f"{p.name}: signal_model entries {sorted(unhandled)} are not "
                    f"batched by PulsarBatch.from_pulsars and will be absent from "
                    f"ensemble simulations (pass GWBConfig / CGWConfig / "
                    f"RoemerConfig to EnsembleSimulator instead)", stacklevel=2)

            bands = []
            for key, entry in getattr(p, "signal_model", {}).items():
                if "system_noise_" not in key:
                    continue
                if float(entry.get("idx", 0.0)) != 0.0:
                    raise ValueError(f"{p.name}.{key} has idx={entry['idx']}; "
                                     f"system bands assume idx=0")
                check_grid(key, entry)
                backend = key.split("system_noise_")[-1]
                bmask = np.zeros(T, dtype=bool)
                bmask[:n] = np.asarray(p.backend_flags)[:n] == backend
                if not bmask.any():
                    raise ValueError(f"{p.name}.{key}: backend {backend!r} has "
                                     f"no TOAs")
                bpsd = np.zeros(n_sys)
                k = min(len(entry["psd"]), n_sys)
                bpsd[:k] = entry["psd"][:k]
                bands.append((bmask, bpsd))
            sys_bands.append(bands)
            targets = {"red_noise": red_psd, "dm_gp": dm_psd,
                       "chrom_gp": chrom_psd}
            for signal, idx in _BATCHED_GPS:
                target = targets[signal]
                entry = getattr(p, "signal_model", {}).get(signal)
                if entry is not None:
                    if float(entry.get("idx", idx)) != idx:
                        raise ValueError(
                            f"{p.name}.{signal} has idx={entry['idx']}; the batch "
                            f"engine assumes the canonical chromatic index {idx}")
                    check_grid(signal, entry)
                    # the ensemble kernel scales by (1400/nu)^idx; a non-default
                    # reference frequency is a constant factor absorbed into the
                    # PSD: sqrt(S)(freqf/nu)^idx = sqrt(S (freqf/1400)^2idx)(1400/nu)^idx
                    freqf = float(entry.get("freqf", 1400.0))
                    k = min(len(entry["psd"]), target.shape[1])
                    target[i, :k] = (np.asarray(entry["psd"][:k])
                                     * (freqf / 1400.0) ** (2.0 * idx))

        t_common = (toas_pad - tmin) / tspan_common * mask

        n_bands = max(1, max((len(b) for b in sys_bands), default=0))
        sys_psd = np.zeros((npsr, n_bands, n_sys))
        sys_mask = np.zeros((npsr, n_bands, T), dtype=bool)
        for i, bands in enumerate(sys_bands):
            for b, (bmask, bpsd) in enumerate(bands):
                sys_mask[i, b] = bmask
                sys_psd[i, b] = bpsd

        return cls(
            t_own=jnp.asarray(t_own, dtype),
            t_common=jnp.asarray(t_common, dtype),
            mask=jnp.asarray(mask),
            freqs=jnp.asarray(freqs, dtype),
            sigma2=jnp.asarray(sigma2, dtype),
            pos=jnp.asarray(pos, dtype),
            red_psd=jnp.asarray(red_psd, dtype),
            dm_psd=jnp.asarray(dm_psd, dtype),
            chrom_psd=jnp.asarray(chrom_psd, dtype),
            epoch_idx=jnp.asarray(epoch_idx),
            ecorr_amp=jnp.asarray(ecorr_amp, dtype),
            sys_psd=jnp.asarray(sys_psd, dtype),
            sys_mask=jnp.asarray(sys_mask),
            df_own=jnp.asarray(df_own, dtype),
            tspan_common=jnp.asarray(tspan_common, dtype),
        )

    @classmethod
    def synthetic(cls, npsr: int = 100, ntoa: int = 780, tspan_years: float = 15.0,
                  toaerr: float = 1e-7, n_red: int = 30, n_dm: int = 100,
                  n_chrom: int = 30,
                  red_log10_A: float = -14.0, red_gamma: float = 13 / 3,
                  dm_log10_A: float = -13.8, dm_gamma: float = 3.0,
                  chrom_log10_A: Optional[float] = None, chrom_gamma: float = 3.0,
                  seed: int = 0, dtype=jnp.float32) -> "PulsarBatch":
        """Fabricate a synthetic uniform-cadence array directly as a batch —
        the benchmark configuration generator (BASELINE.md configs 3-5)."""
        from . import constants as const
        from . import spectrum as spectrum_lib

        rng = np.random.default_rng(seed)
        tspan = tspan_years * const.yr
        toas = np.linspace(0.0, tspan, ntoa)
        costh = rng.uniform(-1, 1, npsr)
        phi = rng.uniform(0, 2 * np.pi, npsr)
        pos = np.stack([np.sqrt(1 - costh**2) * np.cos(phi),
                        np.sqrt(1 - costh**2) * np.sin(phi), costh], axis=-1)

        t_norm = np.tile(toas / tspan, (npsr, 1))
        mask = np.ones((npsr, ntoa), dtype=bool)
        freqs = np.full((npsr, ntoa), 1400.0)
        sigma2 = np.full((npsr, ntoa), toaerr**2)
        f_red = np.arange(1, n_red + 1) / tspan
        f_dm = np.arange(1, n_dm + 1) / tspan
        red = np.asarray(spectrum_lib.powerlaw(f_red, red_log10_A, red_gamma))
        dm = np.asarray(spectrum_lib.powerlaw(f_dm, dm_log10_A, dm_gamma))
        if chrom_log10_A is None:
            chrom = np.zeros(n_chrom)                    # signal off (default)
        else:
            f_chrom = np.arange(1, n_chrom + 1) / tspan
            chrom = np.asarray(spectrum_lib.powerlaw(f_chrom, chrom_log10_A,
                                                     chrom_gamma))

        return cls(
            t_own=jnp.asarray(t_norm, dtype),
            t_common=jnp.asarray(t_norm, dtype),
            mask=jnp.asarray(mask),
            freqs=jnp.asarray(freqs, dtype),
            sigma2=jnp.asarray(sigma2, dtype),
            pos=jnp.asarray(pos, dtype),
            red_psd=jnp.asarray(np.tile(red, (npsr, 1)), dtype),
            dm_psd=jnp.asarray(np.tile(dm, (npsr, 1)), dtype),
            chrom_psd=jnp.asarray(np.tile(chrom, (npsr, 1)), dtype),
            epoch_idx=jnp.tile(jnp.arange(ntoa, dtype=jnp.int32), (npsr, 1)),
            ecorr_amp=jnp.zeros((npsr, ntoa), dtype),
            sys_psd=jnp.zeros((npsr, 1, 1), dtype),
            sys_mask=jnp.zeros((npsr, 1, ntoa), dtype=bool),
            df_own=jnp.asarray(np.full(npsr, 1.0 / tspan), dtype),
            tspan_common=jnp.asarray(tspan, dtype),
        )


def padded_abs_toas(psrs: Sequence) -> np.ndarray:
    """(npsr, max_toa) float64 absolute MJD-second TOAs, zero-padded.

    Companion to :meth:`PulsarBatch.from_pulsars` for the deterministic-signal
    configs (CGW / BayesEphem): those need absolute epochs at host float64
    precision, which the batch's normalized f32 times deliberately discard.
    """
    toas_pad, _ = stack_ragged(
        [np.asarray(p.toas, dtype=np.float64) for p in psrs])
    return toas_pad


def padded_toaerr2(psrs: Sequence) -> np.ndarray:
    """(npsr, max_toa) raw squared TOA errors [s^2], zero-padded.

    Companion to :meth:`PulsarBatch.from_pulsars` for per-realization white
    sampling (``WhiteSampling``): the batch's ``sigma2`` bakes the noisedict's
    efac/equad in (``fake_pta.py:214-217`` semantics), while the sampler needs
    the raw errors the drawn efac multiplies.
    """
    err2, _ = stack_ragged(
        [np.asarray(p.toaerrs, dtype=np.float64) ** 2 for p in psrs])
    return err2


def padded_backend_ids(psrs: Sequence):
    """((npsr, max_toa) int32 backend index, n_backends) from backend flags.

    Backend names index into each pulsar's own sorted unique flag set (the
    sampler draws per (pulsar, backend), so ids need not align across
    pulsars); padding TOAs get id 0. ``n_backends`` is the max count over the
    array — the static draw width ``WhiteSampling`` needs.
    """
    ids = []
    n_backends = 1
    for p in psrs:
        flags = np.asarray(p.backend_flags)
        uniq, idx = np.unique(flags, return_inverse=True)
        n_backends = max(n_backends, len(uniq))
        ids.append(idx.astype(np.int32))
    bid, _ = stack_ragged(ids)
    return bid.astype(np.int32), n_backends


def padded_pdist(psrs: Sequence) -> np.ndarray:
    """(npsr, 2) pulsar-distance (mean, sigma) pairs in kpc.

    Scalar ``pdist`` attributes (copy_array replays store one number) get
    sigma 0.
    """
    out = np.zeros((len(psrs), 2))
    for i, p in enumerate(psrs):
        pd = getattr(p, "pdist", (1.0, 0.2))
        if np.ndim(pd) == 0:
            out[i] = (float(pd), 0.0)
        else:
            out[i] = (float(pd[0]), float(pd[1]))
    return out


def fourier_basis_norm(t_norm, nbin: int, scale=None, bin_offset: int = 0):
    """(…, T, 2, N) cos/sin basis from normalized time: phase = 2 pi n t_norm.

    float32-safe by construction (phase argument <= 2 pi (bin_offset+nbin)).
    ``bin_offset`` starts the harmonic ladder at ``n = bin_offset + 1`` —
    the factorized free-spectrum lanes (docs/SAMPLING.md) restrict a model
    to a bin block by offsetting its basis columns, so a lane's columns are
    bitwise the corresponding columns of the parent model's basis.
    """
    n = jnp.arange(bin_offset + 1, bin_offset + nbin + 1, dtype=t_norm.dtype)
    phase = 2.0 * jnp.pi * t_norm[..., :, None] * n
    basis = jnp.stack([jnp.cos(phase), jnp.sin(phase)], axis=-2)
    if scale is not None:
        basis = basis * scale[..., :, None, None]
    return basis
