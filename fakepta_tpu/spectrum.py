"""Power-spectral-density models for time-correlated pulsar noise processes.

Functional parity with the reference's ``spectrum.py`` (6 models, ``fakepta/spectrum.py:12-86``
in the reference tree), rebuilt as pure ``jax.numpy`` functions so they can sit inside jitted
injection kernels, be vmapped over parameter batches, and differentiated.

Instead of the reference's dynamic ``importlib``/``inspect`` registry
(``fake_pta.py:14-22``), the registry here is explicit: :data:`SPECTRA` maps name ->
:class:`SpectrumModel` carrying the callable and its hyper-parameter names.
:func:`register_spectrum` keeps the reference's extensibility (any new PSD automatically
becomes a legal ``spectrum=`` argument for every injector). ``spec`` / ``spec_params``
aliases preserve the reference's module-level names.

All PSDs map frequency [Hz] -> one-sided timing PSD [s^3] (s^2/Hz), following the
ENTERPRISE convention the reference credits (``spectrum.py:5-9``).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import constants as const


def _softplus(x):
    """Numerically-stable ``log(1 + exp(x))`` for log-space PSD evaluation."""
    return jnp.logaddexp(x, 0.0)


def powerlaw(f, log10_A=-15.0, gamma=13 / 3):
    """Power-law timing PSD: ``A^2/(12 pi^2) fyr^(gamma-3) f^-gamma``.

    Parity: reference ``spectrum.py:12-15``. Evaluated in log space: the naive
    product runs through ~1e-42 intermediates that flush to zero in float32 on TPU,
    so the whole PSD family exponentiates a summed log instead.
    """
    f = jnp.asarray(f)
    ln_psd = (
        2.0 * log10_A * const.ln10
        - jnp.log(12.0 * jnp.pi**2)
        + (gamma - 3.0) * jnp.log(const.fyr)
        - gamma * jnp.log(f)
    )
    return jnp.exp(ln_psd)


def turnover(f, log10_A=-15.0, gamma=4.33, lf0=-8.5, kappa=10 / 3, beta=0.5):
    """Turnover strain spectrum converted to timing PSD via ``hc(f)^2/(12 pi^2 f^3)``.

    Parity: reference ``spectrum.py:18-20``.
    """
    f = jnp.asarray(f)
    # ln hc(f); the low-frequency suppression 1/(1+(f0/f)^k)^beta is a softplus in logs
    ln_hcf = (
        log10_A * const.ln10
        + 0.5 * (3.0 - gamma) * jnp.log(f / const.fyr)
        - beta * _softplus(kappa * (lf0 * const.ln10 - jnp.log(f)))
    )
    return jnp.exp(2.0 * ln_hcf - jnp.log(12.0 * jnp.pi**2) - 3.0 * jnp.log(f))


def t_process(f, log10_A=-15.0, gamma=4.33, alphas=None):
    """Fuzzy power law: per-frequency multipliers ``alphas`` on a power-law PSD.

    Parity: reference ``spectrum.py:23-29``.
    """
    f = jnp.asarray(f)
    alphas = jnp.ones_like(f) if alphas is None else jnp.asarray(alphas)
    return powerlaw(f, log10_A=log10_A, gamma=gamma) * alphas


def t_process_adapt(f, log10_A=-15.0, gamma=4.33, alphas_adapt=None, nfreq=None):
    """Adaptive t-process: fuzz a single frequency bin ``nfreq`` by ``alphas_adapt``.

    Parity: reference ``spectrum.py:32-46``. Implemented with a functional
    ``.at[].set`` instead of in-place mutation so it stays jittable.
    """
    f = jnp.asarray(f)
    if alphas_adapt is None:
        alpha_model = jnp.ones_like(f)
    elif nfreq is None:
        alpha_model = jnp.asarray(alphas_adapt)
    else:
        idx = jnp.rint(jnp.asarray(nfreq)).astype(jnp.int32)
        alpha_model = jnp.ones_like(f).at[idx].set(alphas_adapt)
    return powerlaw(f, log10_A=log10_A, gamma=gamma) * alpha_model


def turnover_knee(f, log10_A=-15.0, gamma=13 / 3, lfb=-8.7, lfk=-8.0, kappa=10 / 3, delta=0.1):
    """Turnover spectrum with an additional high-frequency knee.

    ``hc(f) = A (f/fyr)^((3-gamma)/2) (1 + f/10^lfk)^delta / sqrt(1 + (10^lfb/f)^kappa)``,
    returned as timing PSD. Parity: reference ``spectrum.py:49-66``.
    """
    f = jnp.asarray(f)
    ln_hcf = (
        log10_A * const.ln10
        + 0.5 * (3.0 - gamma) * jnp.log(f / const.fyr)
        + delta * jnp.log1p(f / 10.0**lfk)
        - 0.5 * _softplus(kappa * (lfb * const.ln10 - jnp.log(f)))
    )
    return jnp.exp(2.0 * ln_hcf - jnp.log(12.0 * jnp.pi**2) - 3.0 * jnp.log(f))


def broken_powerlaw(f, log10_A=-15.0, gamma=13 / 3, delta=0.1, log10_fb=-8.5, kappa=0.1):
    """Broken power law with smooth transition at ``10^log10_fb``.

    Parity: reference ``spectrum.py:69-86``.
    """
    f = jnp.asarray(f)
    ln_hcf = (
        log10_A * const.ln10
        + 0.5 * (3.0 - gamma) * jnp.log(f / const.fyr)
        + 0.5 * kappa * (gamma - delta) * _softplus((jnp.log(f) - log10_fb * const.ln10) / kappa)
    )
    return jnp.exp(2.0 * ln_hcf - jnp.log(12.0 * jnp.pi**2) - 3.0 * jnp.log(f))


def free_spectrum(f, log10_rho=None):
    """Free spectral model: independent per-bin power ``rho_i^2`` [s^2] per bin.

    PSD is defined so that ``psd * df == 10^(2 log10_rho)`` on the standard grid
    ``f_i = i/Tspan`` (df = 1/Tspan): ``psd_i = 10^(2 log10_rho_i) * Tspan`` with
    ``Tspan`` inferred as ``1/f_1``. Extension beyond the reference set (ENTERPRISE
    offers the same model); registered so injectors accept ``spectrum='free_spectrum'``.

    The inference is only valid on the standard grid ``f_i = i/Tspan``: a
    concrete non-standard grid (custom ``f_psd`` in the facade injectors)
    raises instead of silently rescaling every bin by the wrong ``Tspan``.
    Traced frequencies (inside jit) skip the check — callers on the standard
    per-pulsar grids (``PulsarBatch``, facade defaults) are pre-validated.
    """
    f = jnp.asarray(f)
    if not isinstance(f, jax.core.Tracer):
        # fakepta: allow[dtype-policy] host-side grid validation, not traced
        f_host = np.asarray(f, dtype=np.float64)
        expect = np.arange(1, f_host.size + 1) * f_host[0]
        # atol=0: PTA grids are ~1e-9 Hz, far below allclose's default atol
        if not np.allclose(f_host, expect, rtol=1e-5, atol=0.0):
            raise ValueError(
                "free_spectrum needs the standard grid f_i = i/Tspan (it "
                "infers Tspan = 1/f[0]); got a non-uniform/offset grid. "
                "Compute the PSD yourself (psd_i = 10**(2*log10_rho_i)/df_i) "
                "and pass it via custom_psd instead")
    log10_rho = jnp.zeros_like(f) if log10_rho is None else jnp.asarray(log10_rho)
    return jnp.exp(2.0 * log10_rho * const.ln10 - jnp.log(f[0]))


@dataclasses.dataclass(frozen=True)
class SpectrumModel:
    """A registered PSD model: the callable and its hyper-parameter names."""

    fn: Callable
    params: Tuple[str, ...]

    def __call__(self, f, **kwargs):
        return self.fn(f, **kwargs)


SPECTRA: Dict[str, SpectrumModel] = {}

# Reference-parity module-level aliases (``fake_pta.py:14-22`` builds `spec`/`spec_params`
# dynamically); kept in sync by :func:`register_spectrum`.
spec: Dict[str, Callable] = {}
spec_params: Dict[str, list] = {}


def register_spectrum(fn: Callable, name: str | None = None, params: Tuple[str, ...] | None = None):
    """Register a PSD model so every injector accepts it by name.

    Replaces the reference's importlib/inspect magic (``fake_pta.py:14-22``) with an
    explicit call. ``params`` defaults to the function's keyword argument names minus ``f``.
    """
    import inspect

    name = name or fn.__name__
    if params is None:
        sig = inspect.signature(fn)
        params = tuple(p for p in sig.parameters if p != "f")
    SPECTRA[name] = SpectrumModel(fn=fn, params=params)
    spec[name] = fn
    spec_params[name] = list(params)
    return fn


for _fn in (powerlaw, turnover, t_process, t_process_adapt, turnover_knee,
            broken_powerlaw, free_spectrum):
    register_spectrum(_fn)


def evaluate(spectrum: str, f, **kwargs):
    """Evaluate a registered PSD by name with keyword hyper-parameters."""
    if spectrum not in SPECTRA:
        raise KeyError(
            f"unknown spectrum {spectrum!r}; registered: {sorted(SPECTRA)}"
        )
    return SPECTRA[spectrum](f, **kwargs)


_CPU_DEVICE = None


def evaluate_host(spectrum: str, f, **kwargs):
    """:func:`evaluate` to a host numpy array, computed on the local CPU backend.

    PSD grids are tiny (tens of bins); evaluating them on the accelerator costs
    a full dispatch + eventual sync — milliseconds of flat latency on a remote
    TPU — while the local CPU backend answers in microseconds. The host result
    feeds jitted kernels (uploaded with the consuming call) and pickles
    directly. Falls back to the default backend when no CPU backend exists.
    """
    global _CPU_DEVICE
    import jax

    import numpy as np
    if _CPU_DEVICE is None:
        try:
            _CPU_DEVICE = jax.devices("cpu")[0]
        except RuntimeError:
            _CPU_DEVICE = False
    if _CPU_DEVICE is False:
        return np.asarray(evaluate(spectrum, f, **kwargs))
    with jax.default_device(_CPU_DEVICE):
        return np.asarray(evaluate(spectrum, f, **kwargs))
