"""North-star benchmark (BASELINE.md): HD-correlated GWB Monte Carlo throughput.

Config: 100-pulsar, 15-year array, weekly cadence (780 TOAs), white + power-law
red + DM noise per pulsar, HD-correlated GWB (A=2e-15, gamma=13/3, 30 components).
Metric: PTA realizations/sec/chip. The baseline target is BASELINE.json's
"10k realizations in < 60 s on a v5e-8", i.e. 10000/(60*8) ~= 20.8 real/s/chip;
``vs_baseline`` is the measured multiple of that target.

Prints exactly one JSON line. Schema (BENCH_r*.json rows are this line, so
the trajectory is self-describing — sourced from the ``fakepta_tpu.obs``
RunReport each ``sim.run()`` attaches):

- ``metric``/``value``/``unit``/``vs_baseline``/``platform``: the headline
  end-to-end throughput, as before;
- ``compile_s``: chunk-program compile time (jax.monitoring, warm-up run);
- ``steady_real_per_s_per_chip``: per-chip rate excluding the
  compile-bearing first chunk of the measured run;
- ``retraces``: unexpected same-signature recompilations during the measured
  run (the retrace guard; anything nonzero means the steady-state number is
  polluted by compiles);
- ``cost_bytes_per_chunk`` (and ``cost_flops_per_chunk``): XLA cost-analysis
  bytes/FLOPs of one chunk program — the roofline inputs as recorded
  artifacts;
- ``os_real_per_s_per_chip`` / ``os_bytes_per_chunk``: the detection-lane
  figures from a second measured run with ``os='hd'`` (the device optimal
  statistic packed beside curves/autos — the configuration detection studies
  actually use, no keep_corr and no (R, P, P) fetch), sourced from that
  run's RunReport; ``obs compare --fail-on-regression`` gates them;
- ``lnlike_evals_per_s_per_chip`` / ``lnlike_bytes_per_chunk``: the
  inference-lane figures from a third measured run with a K=16 CURN
  (log10_A, gamma) grid (``lnlike=InferSpec(...)``, ``fakepta_tpu.infer``:
  the GP-marginalized Woodbury lnL per realization per grid point, inside
  the chunk program). ``lnlike_evals_per_s_per_chip`` is the steady
  realization rate times K — grid lnL evaluations per second per chip —
  and ``lnlike_bytes_per_chunk`` that chunk program's XLA cost-analysis
  bytes; both from the run's RunReport, gated by ``obs compare
  --fail-on-regression`` like the OS rows. The lnlike run uses a reduced
  chunk (the per-realization ``T^T N^-1 r`` moments are O(2M) per pulsar,
  heavier than the packed curves);
- ``pipeline_depth`` / ``pipeline_stall_s`` / ``ckpt_wait_s``: the async
  chunk-pipeline figures from the measured run's RunReport
  (docs/PERFORMANCE.md) — the executed depth, total host time the dispatch
  loop actually waited on (first-chunk staging + depth-bound waits), and
  total checkpoint-append time (overlapped on the writer thread when
  pipelined). Both timings are lower-is-better under ``obs compare``;
- ``intensity_flop_per_byte``: the measured chunk program's arithmetic
  intensity (XLA cost-analysis FLOPs / bytes — the roofline x-coordinate;
  higher-is-better under ``obs compare``), and ``model_bytes_per_chunk``:
  the analytic HBM-traffic model of the same program
  (``fakepta_tpu.ops.megakernel.chunk_bytes_model`` — the TPU-fused
  accounting, recorded beside the measured bytes because XLA:CPU cost
  analysis can neither fuse the draw chain nor see through the
  interpret-mode kernel loop);
- per-mode bytes/chunk rows for the whole-chunk megakernel
  (docs/PERFORMANCE.md): ``cost_bytes_per_chunk_fused`` /
  ``cost_bytes_per_chunk_fused_bf16`` (measured, AOT cost capture of the
  ``use_pallas='mega'`` program at f32 and under the bf16-storage mode —
  no measured run per mode) and ``model_bytes_per_chunk_fused`` /
  ``model_bytes_per_chunk_fused_bf16`` (the analytic model), plus
  ``fused_bytes_reduction_x`` = model_xla / model_fused — the recorded
  roofline acceptance (>= 2x on the flagship config; higher-is-better);
- ``ess_per_s_per_chip`` / ``sample_steps_per_s_per_chip`` / ``rhat_max`` /
  ``accept_rate``: the sampling-lane figures (``fakepta_tpu.sample``,
  docs/SAMPLING.md) from a measured on-device batched-MCMC run — a CURN
  free-spectrum posterior (per-bin ``log10_rho``, the model-independent
  headline workload) sampled by HMC x parallel-tempering chains living
  entirely on device, warm-started from the Laplace fit. ``ess_per_s_per_
  chip`` is the minimum-over-dims effective sample count per second per
  chip and ``sample_steps_per_s_per_chip`` the raw chain-transition
  throughput (steps x chains x rungs); both are higher-better under ``obs
  compare``/``gate``. ``rhat_max`` (split-free cross-chain R-hat, worst
  dim) keeps the lower-is-better default — drifting up past the noise band
  IS a regression — and ``accept_rate`` is an exempt health diagnostic
  (non-monotonic optimum). The accelerator lane samples the flagship
  100-psr array; the CPU stand-in samples a reduced array (the row's
  ``platform`` field disambiguates, as everywhere);
- ``serve_qps_per_chip`` / ``serve_p50_ms`` / ``serve_p99_ms`` /
  ``coalesce_factor`` / ``pad_waste_frac`` / ``serve_speedup_x`` /
  ``serve_serial_qps_per_chip`` / ``serve_retraces`` /
  ``serve_steady_compiles``: the serving-lane figures
  (``fakepta_tpu.serve``, docs/SERVING.md) from the built-in synthetic
  load generator — many small requests coalesced into padded bucket
  dispatches over a warm executable pool, each request on its own RNG
  lane (responses bit-verified against solo runs inside the generator).
  ``serve_qps_per_chip`` is completed requests/s/chip, the p50/p99 are
  end-to-end request latencies (lower-better), ``coalesce_factor`` the
  mean requests per dispatch (higher-better), ``pad_waste_frac`` the mean
  padded-slot fraction (lower-better), and ``serve_speedup_x`` the
  request-throughput multiple over serial per-request ``run()`` dispatch
  of the same request list (the acceptance figure, >= 5x). The retrace/
  steady-compile counters must stay 0 — a warm-pool request never pays a
  recompile after warmup. The accelerator lane serves the flagship-sized
  spec; the CPU stand-in a reduced one (``platform`` disambiguates);
- ``fleet_qps`` / ``fleet_qps_per_chip`` / ``fleet_p50_ms`` /
  ``fleet_p99_ms`` / ``fleet_speedup_x`` / ``fleet_warm_hit_rate`` /
  ``fleet_failovers`` / ``fleet_lost_requests`` /
  ``fleet_steady_compiles``: the multi-replica serve-fleet lane
  (``fakepta_tpu.serve.fleet``, docs/SERVING.md "Fleet";
  ``benchmarks/suite.py`` config 13): N subprocess ``ServePool`` replicas
  behind the spec-hash consistent-hash router, measured by
  ``run_loadgen(fleet=N)`` against ONE pool serving the same traffic.
  ``fleet_speedup_x`` (higher-better) is the scale-out multiple — on the
  single-core CPU stand-in it measures aggregate warm-capacity scaling
  (the traffic's spec working set exceeds one pool's LRU ``max_specs``);
  on multi-chip hosts replica dispatchers additionally run in parallel.
  ``fleet_warm_hit_rate`` (higher-better) is the fraction of requests
  served by their spec's ring owner; ``fleet_failovers`` counts mid-flight
  re-dispatches after the lane's scripted replica kill, and
  ``fleet_lost_requests`` MUST stay 0 — every accepted request completes,
  failed-over responses bit-verified against solo runs (the per-request
  RNG-lane contract). ``fleet_steady_compiles`` must stay 0: all replicas
  share the persistent compile cache, so cold starts and failover shard
  absorption are cache loads, not compiles;
- ``fleet_heartbeat_misses`` / ``fleet_breaker_opens`` /
  ``fleet_timeouts`` / ``fleet_joins`` / ``fleet_drains`` /
  ``scale_events`` / ``fleet_join_steady_compiles``: the fleet lifecycle
  lane (``fakepta_tpu.serve.health``/``.autoscale``, docs/RELIABILITY.md
  "Fleet lifecycle"; ``benchmarks/suite.py`` config 15 runs the elastic
  chaos A/B — ramp, wedge one replica's heartbeats, SIGKILL another,
  autoscale a third in). Heartbeat misses and breaker opens keep the
  lower-is-better default: the scripted wedge produces a known floor,
  and growth past it means replicas are degrading unscripted.
  ``fleet_timeouts`` and ``fleet_lost_requests`` MUST stay 0 — a wedged
  replica is breakered out of band, never discovered by a client timing
  out into it. ``fleet_joins``/``fleet_drains``/``scale_events`` are
  exempt membership-churn shape facts, and
  ``fleet_join_steady_compiles`` must stay 0: an autoscale-joined
  replica prewarms its absorbed shard from the shared compile cache
  (warm loads, not compiles);
- ``fleet_scrapes`` / ``fleet_scrape_errors`` / ``fleet_alerts`` /
  ``telemetry_overhead_frac`` / ``trace_flows``: the telemetry-plane lane
  (``fakepta_tpu.obs.telemetry``, docs/OBSERVABILITY.md; rides the
  config 15 chaos run). ``fleet_scrapes`` (exempt — scrape volume is the
  heartbeat cadence, a shape fact) counts publisher snapshots the health
  plane ingested over the heartbeat's mux'd connections;
  ``fleet_scrape_errors`` and ``fleet_alerts`` keep the lower-is-better
  default (the scripted chaos produces a known alert floor; growth past
  it is replicas degrading unscripted); ``telemetry_overhead_frac`` is
  the interleaved A/B qps cost of scraping on vs off (lower-better,
  acceptance <= 0.02) and ``trace_flows`` (exempt shape fact) the number
  of request trace-id flow chains the exported Chrome trace carries;
- ``append_latency_ms`` / ``restage_ms`` / ``append_speedup_x`` /
  ``stream_appends`` / ``stream_toas`` / ``stream_rebuckets`` /
  ``stream_recompiles``: the streaming-ingestion lane
  (``fakepta_tpu.stream``, docs/STREAMING.md; ``benchmarks/suite.py``
  config 14 is the same recipe). A stream accumulates bulk history on its
  frozen Fourier grids, then one observing epoch arrives:
  ``append_latency_ms`` (lower-better) is the steady-state cost of the
  additive rank-k Woodbury-moment append, ``restage_ms`` the full
  recompute of the same store through the same kernels, and
  ``append_speedup_x`` (higher-better) their ratio — the acceptance
  figure, >= 5x at the flagship config (the append is O(new-epoch), the
  restage O(history)). ``stream_recompiles`` MUST stay 0: appends within
  the current (block bucket, epoch capacity) rungs reuse compiled
  executables, and any retrace means the bucket ladder stopped covering
  the traffic (``stream_appends``/``stream_toas``/``stream_rebuckets``
  are exempt shape facts). The accelerator lane streams the flagship
  100-psr x 15-yr array with ECORR epoch blocks; the CPU stand-in a
  reduced one (``platform`` disambiguates);
- ``faults_retries`` / ``faults_degradations`` / ``faults_rollbacks``: the
  measured run's recovery counters (``fakepta_tpu.faults``,
  docs/RELIABILITY.md) — transient dispatch/drain retries, degradation-
  ladder steps (mega->fused->xla, bf16->f32, donation-off) and torn-
  checkpoint rollbacks that engaged during the benchmark. All three are
  expected 0 on a healthy round; any growth past the zero history flags
  under ``obs gate``, because a benchmark number produced THROUGH the
  recovery ladder is not a clean steady-state figure.
  ``benchmarks/suite.py`` config 12 additionally measures the recovery
  overhead itself (``fault_recovery_overhead_frac``: wall-clock cost of
  one injected-and-retried transient per run, bit-identity asserted);
- ``tuned`` / ``tune_probe_s`` / ``tuned_real_per_s_per_chip`` /
  ``tuned_speedup_x``: the autotuner lane (``fakepta_tpu.tune``,
  docs/TUNING.md). ``tuned`` flags that autotuned knobs rode the A/B run
  (exempt under ``obs compare``/``gate`` — a run-shape fact);
  ``tune_probe_s`` is the wall-clock the search spent probing this round
  (0 on a warm store — the persisted TunedConfig made the search one
  file read; lower-better, growth means the store stopped warming);
  ``tuned_real_per_s_per_chip`` the tuned run's steady throughput and
  ``tuned_speedup_x`` its multiple of the hand-set measurement above on
  the same simulator (both higher-better; the search always probes the
  hand-set default candidate first, so the tuner can select but never
  silently lose to it);
- ``gw_hit_rate`` / ``gw_device_s_saved`` / ``gw_p99_ms_under_quota`` /
  ``gw_throttles`` / ``gw_cutover_ms`` / ``gw_requests`` / ``gw_tenants``
  / ``gw_coalesced`` / ``gw_verified``: the multi-tenant gateway lane
  (``fakepta_tpu.gateway``, docs/GATEWAY.md; ``benchmarks/suite.py``
  config 16 — a Zipfian hot-spec tenant mix against a gateway-fronted
  fleet). ``gw_hit_rate`` (higher-better via the ``_hit_rate`` suffix,
  acceptance >= 0.5 at the scripted skew) is the fraction of admitted
  requests served from the content-addressed result store or folded into
  an in-flight identical leader; every hit is bit-verified against a solo
  engine run on the same RNG lane before the row is recorded (the row is
  REFUSED on any mismatch, so ``gw_verified`` — exempt shape fact — counts
  proofs, not samples). ``gw_device_s_saved`` (higher-better) is the
  producing runs' device-seconds not re-spent on hits;
  ``gw_p99_ms_under_quota`` (lower-better) the admitted-request p99 across
  tenants while the hot tenant is throttled at its fair share;
  ``gw_cutover_ms`` (lower-better) the fence-to-swap wall clock of the
  mid-load frozen-grid migration cutover (TOA conservation and the
  append-equals-restage oracle enforced, 0 dropped appends or the row is
  refused). ``gw_throttles`` / ``gw_requests`` / ``gw_tenants`` are
  exempt traffic-shape facts (the scripted Zipf overload produces
  throttles by design) and ``gw_coalesced`` (exempt) counts requests that
  rode another tenant's in-flight dispatch — race-timing dependent, so a
  shape fact, while the hits it produces still bit-verify;
- ``peak_hbm_bytes``: the measured run's HBM watermark from the RunReport's
  memwatch lane (allocator ``peak_bytes_in_use`` max-aggregated over local
  devices and over the low-rate in-run sampler where the backend exposes
  allocator stats; the static-reservation + live-packed-buffer model on the
  CPU stand-in). Lower-is-better under ``obs compare`` (the default
  direction) and banded by ``obs gate`` like every other row metric;
- ``fallback``: present when the accelerator was unreachable (CPU stand-in).
  ``benchmarks/suite.py`` rows carry the same ``platform``/``fallback``
  pair, so CPU stand-in rounds are distinguishable across the whole
  trajectory;
- ``scenario`` / ``scn_real_per_s_per_chip`` / ``scn_ess_per_s_per_chip``
  / ``scn_peak_hbm_bytes`` / ``scn_append_p99_ms``: the scenario
  golden-run lane (``fakepta_tpu.scenarios``, docs/SCENARIOS.md; emitted
  by ``python -m fakepta_tpu.scenarios run`` and ``benchmarks/suite.py``
  config 17). ``scenario`` is the registered scenario name — row-identity
  like ``platform``, never banded, and ``obs gate`` groups history by it
  so an ``ng15`` golden row only bands against ``ng15`` history.
  ``scn_real_per_s_per_chip`` and ``scn_ess_per_s_per_chip`` (both
  higher-better via the ``_per_s_per_chip`` suffix) are the scenario
  ensemble's steady simulation throughput and the sampler lane's ESS
  rate on the scenario's array; ``scn_peak_hbm_bytes`` (lower-better) the
  scenario run's HBM watermark, and ``scn_append_p99_ms`` (lower-better)
  the p99 append latency under the scenario's telescope-cadence
  ``AppendRequest`` schedule (zero-recompile contract enforced, same as
  the main stream lane).
- ``fs_lane_count`` / ``fs_speedup_x`` / ``fs_ess_per_s_per_chip`` /
  ``fs_wall_s_total`` / ``fs_wall_s_critical`` / ``fs_oracle_max_err`` /
  ``fs_recompiles`` / ``fs_refresh_ms`` / ``fs_full_refresh_ms`` /
  ``fs_refresh_speedup_x`` / ``fs_lanes_touched`` / ``fs_bins_touched``:
  the factorized free-spectrum lane (``fakepta_tpu.sample.factorized``,
  ``stream.FactorizedRefresher``, docs/SAMPLING.md; emitted by
  ``benchmarks/suite.py`` config 18). ``fs_lane_count`` /
  ``fs_lanes_touched`` / ``fs_bins_touched`` are decomposition/scenario
  shape facts (exempt); ``fs_speedup_x`` (factorized-vs-joint ESS/s
  multiple), ``fs_refresh_speedup_x`` (incremental-vs-full refresh
  multiple) and ``fs_ess_per_s_per_chip`` (critical-path per-chip ESS
  rate, ``_per_s_per_chip`` suffix) are higher-better — the lane's whole
  point; ``fs_oracle_max_err`` (the f64 additivity defect — config 18
  REFUSES to record a row when it exceeds the exactness gate),
  ``fs_recompiles`` (steady-state lane retraces, zero-compile contract),
  ``fs_refresh_ms`` / ``fs_full_refresh_ms`` and ``fs_wall_s_total`` /
  ``fs_wall_s_critical`` are lower-better costs.

A new row is gated against this history with ``python -m fakepta_tpu.obs
gate row.json`` — MAD noise bands over same-``platform`` (and, for
scenario golden rows, same-``scenario``) rows, so the CPU stand-in rounds
never band an accelerator round and no scenario bands another
(docs/OBSERVABILITY.md).

Backend selection: the dead-tunnel probe verdict is cached to a temp file
scoped to this process tree, and ``FAKEPTA_TPU_BENCH_BACKEND=cpu`` (or any
backend name) skips the probe entirely — see ``__graft_entry__`` — so
CPU-fallback bench runs no longer pay minutes of probe dead air.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def main():
    import jax

    from __graft_entry__ import _backend_reachable

    # the remote-TPU tunnel's backend init BLOCKS forever when the tunnel is
    # dead (observed in this environment); probe it in a subprocess (shared
    # detector) and fall back so the benchmark always reports a labeled line
    fallback = not _backend_reachable()
    if fallback:
        print("bench: accelerator backend unavailable; falling back to the "
              "CPU backend", file=sys.stderr)
        jax.config.update("jax_platforms", "cpu")

    from fakepta_tpu import spectrum as spectrum_lib
    from fakepta_tpu.batch import PulsarBatch
    from fakepta_tpu.parallel.mesh import make_mesh
    from fakepta_tpu.parallel.montecarlo import EnsembleSimulator, GWBConfig
    from fakepta_tpu.scenarios import registry as scn_registry

    n_devices = len(jax.devices())
    # registry-sourced flagship (bit-identical to the historical literal;
    # the unregistered-scenario rule keeps ad-hoc copies out)
    batch = scn_registry.flagship_batch()
    tspan = float(batch.tspan_common)
    f = np.arange(1, 31) / tspan
    psd = np.asarray(spectrum_lib.powerlaw(f, log10_A=np.log10(2e-15), gamma=13 / 3))
    sim = EnsembleSimulator(batch, gwb=GWBConfig(psd=psd, orf="hd"),
                            mesh=make_mesh(jax.devices()))

    # 100k realizations in 10k chunks (a chunk fits v5e HBM at ~3 GB peak; the
    # chunks pipeline on device and outputs are fetched once at the end, so a
    # longer run measures steady-state throughput instead of the ~80 ms
    # flat-latency host round-trip of the remote-TPU tunnel). The CPU fallback
    # runs a reduced count so a dead tunnel still yields a labeled number.
    # Platform identity is single-sourced through the tuner's fingerprint
    # (fakepta_tpu.tune) — the same probe `obs gate`'s same-platform row
    # matching and suite.py's platform column read.
    from fakepta_tpu import tune as tune_mod
    platform = tune_mod.fingerprint().platform
    nreal, chunk = (100_000, 10_000) if platform != "cpu" else (2_000, 1_000)
    warm = sim.run(chunk, seed=99, chunk=chunk)  # compile + warm up
    t0 = time.perf_counter()
    out = sim.run(nreal, seed=1, chunk=chunk)
    elapsed = time.perf_counter() - t0
    # not a bare assert: a stripped (-O) run must not record garbage as a result
    if out["curves"].shape[0] != nreal or not np.all(np.isfinite(out["curves"])):
        raise RuntimeError("benchmark produced wrong-shaped or non-finite output")

    per_chip = nreal / elapsed / n_devices
    baseline = 10_000 / (60.0 * 8)               # the v5e-8 target, per chip
    # obs telemetry (see module docstring for the field schema): compile time
    # from the warm-up run's report (the measured run reuses the executable),
    # steady-state rate / retraces from the measured run's report
    warm_rep, rep = warm["report"], out["report"]
    row = {
        "metric": "PTA realizations/sec/chip (100 psr, 15 yr, HD-correlated GWB)",
        "value": round(per_chip, 2),
        "unit": "realizations/s/chip",
        "vs_baseline": round(per_chip / baseline, 2),
        "platform": platform,
        "compile_s": round(warm_rep.compile_s, 3),
        "steady_real_per_s_per_chip": round(
            rep.steady_real_per_s_per_chip(), 2),
        "retraces": rep.retraces,
    }
    if rep.cost.get("bytes_per_chunk"):
        row["cost_bytes_per_chunk"] = rep.cost["bytes_per_chunk"]
    if rep.cost.get("flops_per_chunk"):
        row["cost_flops_per_chunk"] = rep.cost["flops_per_chunk"]
    rep_sum = rep.summary()
    for key in ("intensity_flop_per_byte", "model_bytes_per_chunk"):
        if rep_sum.get(key):
            row[key] = rep_sum[key]
    row["pipeline_depth"] = rep_sum.get("pipeline_depth", 0)
    row["pipeline_stall_s"] = rep_sum.get("pipeline_stall_s", 0.0)
    row["ckpt_wait_s"] = rep_sum.get("ckpt_wait_s", 0.0)
    if rep_sum.get("peak_hbm_bytes"):
        row["peak_hbm_bytes"] = rep_sum["peak_hbm_bytes"]
    # recovery health (fakepta_tpu.faults, docs/RELIABILITY.md): the
    # measured run's recovery counters. Nonzero means the engine retried,
    # degraded or rolled back mid-benchmark — the throughput figure is
    # then not a clean steady-state number (lower-is-better under
    # `obs compare`/`gate`, and any growth past the zero history flags)
    for key, counter in (("faults_retries", "faults.retries"),
                         ("faults_degradations", "faults.degradations"),
                         ("faults_rollbacks", "faults.rollbacks")):
        row[key] = int(rep.counters.get(counter, 0))

    # the autotuner lane (fakepta_tpu.tune, docs/TUNING.md): search the
    # dispatch-knob space for THIS platform fingerprint (warm store =>
    # zero probes, zero compiles — tune_probe_s records either way), then
    # A/B a tuned run() against the hand-set measurement above on the
    # same simulator. tuned_speedup_x >= ~1 is the acceptance: the tuner
    # may never lose to the hand-set knobs it was seeded with (the
    # default candidate is always probed first), and `obs gate` bands the
    # ratio across rounds.
    tuned_cfg, tune_info = tune_mod.search(
        batch, gwb=GWBConfig(psd=psd, orf="hd"), nreal_hint=nreal,
        max_candidates=8)
    row["tuned"] = 1
    row["tune_probe_s"] = round(float(tune_info["probe_s"]), 2)
    chunk_t = int(tuned_cfg.knobs.get("chunk", chunk))
    nreal_ab = min(nreal, 4 * max(chunk_t, chunk))
    # warm the tuned-shape executable first (mirrors the probe protocol
    # and the hand-set side's own warm-up above): the pipelined steady
    # split credits the compile-bearing dispatch's realizations but not
    # its device time, so an unwarmed A/B would under-report the tuned
    # side by ~chunk/nreal. The A/B itself interleaves hand-set and
    # tuned measurements best-of-2 — comparing a fresh tuned number
    # against the minutes-old headline would fold host drift into the
    # speedup
    sim.run(chunk_t, seed=96, tuned=tuned_cfg)
    hand_rate = tuned_rate = 0.0
    for _ in range(2):
        out_h = sim.run(nreal_ab, seed=1, chunk=chunk)
        hand_rate = max(hand_rate,
                        out_h["report"].steady_real_per_s_per_chip())
        out_t = sim.run(nreal_ab, seed=1, tuned=tuned_cfg)
        tuned_rate = max(tuned_rate,
                         out_t["report"].steady_real_per_s_per_chip())
    row["tuned_real_per_s_per_chip"] = round(tuned_rate, 2)
    if hand_rate > 0:
        row["tuned_speedup_x"] = round(tuned_rate / hand_rate, 3)

    # the detection lane (fakepta_tpu.detect): same flagship program with the
    # on-device optimal statistic packed beside curves/autos — measured
    # separately because its chunk program differs (one extra contraction)
    nreal_os = min(nreal, 2 * chunk)
    sim.run(chunk, seed=98, chunk=chunk, os="hd")        # compile + warm up
    out_os = sim.run(nreal_os, seed=1, chunk=chunk, os="hd")
    os_rep = out_os["report"]
    os_sum = os_rep.summary()
    row["os_real_per_s_per_chip"] = os_sum.get(
        "os_real_per_s_per_chip",
        round(os_rep.steady_real_per_s_per_chip(), 2))
    if os_sum.get("os_bytes_per_chunk"):
        row["os_bytes_per_chunk"] = os_sum["os_bytes_per_chunk"]

    # the inference lane (fakepta_tpu.infer): flagship + K=16 CURN grid of
    # GP-marginalized Woodbury lnL per realization, inside the chunk
    # program. Reduced chunk: the lane's per-realization moments are O(2M)
    # per pulsar (see the module docstring schema).
    from fakepta_tpu.infer import (ComponentSpec, FreeParam, InferSpec,
                                   LikelihoodSpec, theta_grid)
    lnl_model = LikelihoodSpec(components=(
        ComponentSpec(target="red", spectrum="batch"),
        ComponentSpec(target="dm", spectrum="batch"),
        ComponentSpec(target="curn", nbin=30, free=(
            FreeParam("log10_A", np.log10(2e-15) + np.array([-0.5, 0.5])),
            FreeParam("gamma", (3.0, 6.0)))),
    ))
    lnl_spec = InferSpec(model=lnl_model, theta=theta_grid(lnl_model, 4))
    chunk_lnl = max(n_devices, chunk // 5)
    nreal_lnl = 2 * chunk_lnl
    sim.run(chunk_lnl, seed=97, chunk=chunk_lnl, lnlike=lnl_spec)  # warm up
    out_lnl = sim.run(nreal_lnl, seed=1, chunk=chunk_lnl, lnlike=lnl_spec)
    lnl_sum = out_lnl["report"].summary()
    row["lnlike_evals_per_s_per_chip"] = lnl_sum.get(
        "lnlike_evals_per_s_per_chip", 0.0)
    if lnl_sum.get("lnlike_bytes_per_chunk"):
        row["lnlike_bytes_per_chunk"] = lnl_sum["lnlike_bytes_per_chunk"]
    # the sampling lane (fakepta_tpu.sample): on-device batched MCMC — a
    # CURN free-spectrum posterior (per-bin log10_rho) characterized by HMC
    # x tempering chains with zero host round-trips in the chain loop
    # (docs/SAMPLING.md). The flagship array on an accelerator; a reduced
    # array on the CPU stand-in (the Laplace staging + per-step batched
    # Cholesky make the 100-psr config intractable host-side) — rows are
    # disambiguated by `platform` like every stand-in figure.
    from fakepta_tpu.sample import SampleSpec, SamplingRun
    if platform != "cpu":
        s_batch, s_chains, s_steps, s_warm = batch, 256, 512, 256
    else:
        s_batch = PulsarBatch.synthetic(npsr=8, ntoa=96, tspan_years=15.0,
                                        toaerr=1e-7, n_red=8, n_dm=8, seed=0)
        s_chains, s_steps, s_warm = 16, 256, 128
    s_model = LikelihoodSpec(components=(
        ComponentSpec(target="red", spectrum="batch"),
        ComponentSpec(target="dm", spectrum="batch"),
        ComponentSpec(target="curn", nbin=6, spectrum="free_spectrum", free=(
            FreeParam("log10_rho", (-9.0, -5.0), per_bin=True),)),
    ))
    s_spec = SampleSpec(model=s_model, n_chains=s_chains, n_temps=2,
                        step_size=0.35, n_leapfrog=10, thin=2, warmup=s_warm)
    sampler = SamplingRun(s_batch, s_spec, mesh=make_mesh(jax.devices()),
                          data_seed=7)
    s_out = sampler.run(s_steps, seed=7, segment=128, pipeline_depth=2)
    for key in ("ess_per_s_per_chip", "sample_steps_per_s_per_chip",
                "rhat_max", "accept_rate"):
        row[key] = s_out["summary"][key]

    # the serving lane (fakepta_tpu.serve, docs/SERVING.md): the built-in
    # synthetic load generator drives a warm pool + microbatch coalescing
    # scheduler with many small requests and measures request throughput,
    # latency SLOs and the speedup over serial per-request run() dispatch
    # (responses are bit-verified against solo runs inside the generator).
    # The accelerator serves a flagship-sized spec; the CPU stand-in a
    # reduced one — rows disambiguate by `platform`, as everywhere.
    from fakepta_tpu.serve import ArraySpec, ServeConfig, run_loadgen
    if platform != "cpu":
        serve_spec = scn_registry.get("flagship_100").serve_spec()
        serve_requests, serve_sizes = 128, (8, 16, 32, 64)
        serve_buckets = tuple(b for b in (64, 128, 256, 512)
                              if b % n_devices == 0)
    else:
        # CPU stand-in: small array, many tiny requests — the regime where
        # the per-dispatch fixed cost the scheduler amortizes is visible
        # without an accelerator's ~80 ms tunnel round-trip (measured
        # 5.6-5.9x over serial dispatch on this config)
        serve_spec = ArraySpec(npsr=16, ntoa=128, n_red=8, n_dm=8,
                               gwb_ncomp=8)
        serve_requests, serve_sizes = 128, (1, 2, 4)
        serve_buckets = tuple(b for b in (16, 128)
                              if b % n_devices == 0)
    serve_row = run_loadgen(
        spec=serve_spec, mesh=make_mesh(jax.devices()),
        n_requests=serve_requests, sizes=serve_sizes, kind="sim",
        baseline=True, verify=2, seed=5,
        config=ServeConfig(buckets=serve_buckets))
    for key in ("serve_qps_per_chip", "serve_p50_ms", "serve_p99_ms",
                "coalesce_factor", "pad_waste_frac", "serve_speedup_x",
                "serve_serial_qps_per_chip", "serve_retraces",
                "serve_steady_compiles"):
        if key in serve_row:
            row[key] = serve_row[key]

    # per-mode bytes/chunk (the megakernel tentpole, docs/PERFORMANCE.md):
    # AOT cost capture of the fused whole-chunk program and its
    # bf16-storage mode on the same flagship batch — a compile, not a
    # measured run, so the roofline acceptance is recorded every round
    sim_mega = EnsembleSimulator(batch, gwb=GWBConfig(psd=psd, orf="hd"),
                                 mesh=make_mesh(jax.devices()),
                                 use_pallas="mega")
    for name, cost in (("fused", sim_mega.chunk_cost(chunk)),
                       ("fused_bf16",
                        sim_mega.chunk_cost(chunk, precision="bf16"))):
        if cost.get("bytes_per_chunk"):
            row[f"cost_bytes_per_chunk_{name}"] = cost["bytes_per_chunk"]
        if cost.get("model_bytes_per_chunk"):
            row[f"model_bytes_per_chunk_{name}"] = \
                cost["model_bytes_per_chunk"]
    if row.get("model_bytes_per_chunk") and \
            row.get("model_bytes_per_chunk_fused"):
        row["fused_bytes_reduction_x"] = round(
            row["model_bytes_per_chunk"]
            / row["model_bytes_per_chunk_fused"], 2)

    # the streaming lane (fakepta_tpu.stream, docs/STREAMING.md): a stream
    # accumulates bulk history on its frozen grids, then one observing
    # epoch arrives — the A/B is the additive rank-k append against a full
    # restage of the same store on the same kernels (O(new-epoch) vs
    # O(history)); append_speedup_x is the acceptance figure (>= 5x at
    # the flagship config) and stream_recompiles the zero-expected ladder
    # canary. Sizes mirror benchmarks/suite.py config 14.
    from fakepta_tpu.stream.bench import run_append_ab
    yr_s = 365.25 * 86400.0
    if platform != "cpu":
        stream_row = run_append_ab(npsr=100, ntoa=780, tspan_years=15.0,
                                   n_red=30, n_dm=100, nbin=10,
                                   history=780, epoch_width=8,
                                   ecorr_dt=15.0 * yr_s / 64, seed=0)
    else:
        stream_row = run_append_ab(npsr=16, ntoa=128, tspan_years=15.0,
                                   n_red=8, n_dm=8, nbin=8, history=1024,
                                   epoch_width=8,
                                   ecorr_dt=15.0 * yr_s / 50, seed=0)
    for key in ("append_latency_ms", "restage_ms", "append_speedup_x",
                "stream_appends", "stream_toas", "stream_rebuckets",
                "stream_recompiles"):
        row[key] = stream_row[key]

    # the fleet lane (fakepta_tpu.serve.fleet, docs/SERVING.md "Fleet"):
    # 3 subprocess replicas behind the spec-hash router vs ONE pool on
    # the same multi-spec traffic, one replica SIGKILLed at half load —
    # the scale-out multiple, failover health (zero lost requests,
    # failed-over responses bit-verified inside the generator) and
    # shared-compile-cache cold starts (module docstring schema;
    # benchmarks/suite.py config 13 is the bigger form). Runs LAST: its
    # shared cache dir rebinds the process-wide jax compilation cache.
    import tempfile
    if platform != "cpu":
        fleet_spec = ArraySpec(npsr=40, ntoa=260, n_red=10, n_dm=10,
                               gwb_ncomp=10)
        fleet_requests = 96
    else:
        fleet_spec = ArraySpec(npsr=8, ntoa=64, n_red=4, n_dm=4,
                               gwb_ncomp=4)
        fleet_requests = 48
    fleet_row = run_loadgen(
        spec=fleet_spec, fleet=3, fleet_transport="process",
        n_requests=fleet_requests, sizes=(1, 2, 4), n_specs=6, seed=5,
        baseline=True, verify=2, kill_one_at=0.5,
        compile_cache_dir=tempfile.mkdtemp(prefix="fleet_cache_"))
    for key in ("fleet_qps", "fleet_qps_per_chip", "fleet_p50_ms",
                "fleet_p99_ms", "fleet_speedup_x", "fleet_warm_hit_rate",
                "fleet_failovers", "fleet_lost_requests",
                "fleet_steady_compiles", "fleet_retraces",
                "fleet_solo_qps", "fleet_solo_p50_ms"):
        if key in fleet_row:
            row[key] = fleet_row[key]

    if fallback:
        row["fallback"] = "accelerator backend unavailable; CPU stand-in"
    print(json.dumps(row))


if __name__ == "__main__":
    main()
