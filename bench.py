"""North-star benchmark (BASELINE.md): HD-correlated GWB Monte Carlo throughput.

Config: 100-pulsar, 15-year array, weekly cadence (780 TOAs), white + power-law
red + DM noise per pulsar, HD-correlated GWB (A=2e-15, gamma=13/3, 30 components).
Metric: PTA realizations/sec/chip. The baseline target is BASELINE.json's
"10k realizations in < 60 s on a v5e-8", i.e. 10000/(60*8) ~= 20.8 real/s/chip;
``vs_baseline`` is the measured multiple of that target.

Prints exactly one JSON line.
"""

import json
import time

import numpy as np


def main():
    import jax

    from fakepta_tpu import spectrum as spectrum_lib
    from fakepta_tpu.batch import PulsarBatch
    from fakepta_tpu.parallel.mesh import make_mesh
    from fakepta_tpu.parallel.montecarlo import EnsembleSimulator, GWBConfig

    n_devices = len(jax.devices())
    batch = PulsarBatch.synthetic(npsr=100, ntoa=780, tspan_years=15.0,
                                  toaerr=1e-7, n_red=30, n_dm=100, seed=0)
    tspan = float(batch.tspan_common)
    f = np.arange(1, 31) / tspan
    psd = np.asarray(spectrum_lib.powerlaw(f, log10_A=np.log10(2e-15), gamma=13 / 3))
    sim = EnsembleSimulator(batch, gwb=GWBConfig(psd=psd, orf="hd"),
                            mesh=make_mesh(jax.devices()))

    # 100k realizations in 10k chunks (a chunk fits v5e HBM at ~3 GB peak; the
    # chunks pipeline on device and outputs are fetched once at the end, so a
    # longer run measures steady-state throughput instead of the ~80 ms
    # flat-latency host round-trip of the remote-TPU tunnel)
    nreal = 100_000
    chunk = 10_000
    sim.run(chunk, seed=99, chunk=chunk)         # compile + warm up
    t0 = time.perf_counter()
    out = sim.run(nreal, seed=1, chunk=chunk)
    elapsed = time.perf_counter() - t0
    assert out["curves"].shape[0] == nreal and np.all(np.isfinite(out["curves"]))

    per_chip = nreal / elapsed / n_devices
    baseline = 10_000 / (60.0 * 8)               # the v5e-8 target, per chip
    print(json.dumps({
        "metric": "PTA realizations/sec/chip (100 psr, 15 yr, HD-correlated GWB)",
        "value": round(per_chip, 2),
        "unit": "realizations/s/chip",
        "vs_baseline": round(per_chip / baseline, 2),
    }))


if __name__ == "__main__":
    main()
