"""Null-vs-injected GWB detection statistic over Monte-Carlo ensembles.

The point of simulating PTA datasets (the reference's use case; BASELINE.md
config 5 is literally "null vs injected") is calibrating detection statistics:
how well does an angular-correlation statistic separate an array WITH an
HD-correlated background from one with uncorrelated noise only?

This script runs both ensembles through the sharded device engine
(:class:`fakepta_tpu.parallel.montecarlo.EnsembleSimulator`), projects each
realization's binned correlation curve onto the Hellings-Downs template
(a matched-filter statistic), and computes the noise-weighted optimal
statistic on the device OS lane (``run(os=...)``, ``fakepta_tpu.detect``) —
per-realization amp2 packed beside curves/autos, with no ``keep_corr=True``
and no (R, P, P) correlation fetch (``--legacy-host-os`` keeps the old host
path for A/B). It reports the separation of the two distributions:

    python examples/detection_statistic.py                  # defaults
    python examples/detection_statistic.py --npsr 100 --nreal 10000
    python examples/detection_statistic.py --platform cpu   # no TPU needed

Prints one JSON line with the two distribution summaries and the detection
significance (mean shift of the injected distribution in units of the null's
standard deviation), plus the false-alarm/detection rates at the null's 95th
percentile.
"""

import argparse
import json
import pathlib
import sys

import numpy as np

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


def hd_template(bin_centers):
    """Hellings-Downs curve on the statistic's angular bins (ref :62-71)."""
    x = (1.0 - np.cos(bin_centers)) / 2.0
    with np.errstate(divide="ignore", invalid="ignore"):
        hd = 1.5 * x * np.log(x) - 0.25 * x + 0.5
    return np.where(x > 0, hd, 0.5)


def matched_filter(curves, autos, centers):
    """Project each realization's binned curve onto the HD template.

    ``curves`` are raw binned pair correlations (seconds^2); normalizing by the
    ensemble-mean autocorrelation makes the statistic dimensionless and
    comparable between null and injected runs.
    """
    t = hd_template(centers)
    t = t / np.linalg.norm(t)
    return (curves @ t) / np.maximum(autos.mean(), 1e-300)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--npsr", type=int, default=40)
    ap.add_argument("--ntoa", type=int, default=260)
    ap.add_argument("--nreal", type=int, default=2000)
    ap.add_argument("--chunk", type=int, default=1000)
    # default amplitude gives a visible separation (~2 sigma at 40 psr/1k
    # realizations); the astrophysically-favored 2e-15 needs the full
    # noise-weighted optimal statistic (or a much bigger array) to stand out
    ap.add_argument("--log10-A", type=float, default=-14.0)
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--platform", default=None,
                    help="force a jax platform (e.g. cpu)")
    ap.add_argument("--legacy-host-os", action="store_true",
                    help="A/B path: fetch the full (R, P, P) correlation "
                         "tensors (keep_corr=True) and run the host "
                         "optimal_statistic instead of the device OS lane")
    args = ap.parse_args()
    import jax
    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    from fakepta_tpu import spectrum as spectrum_lib
    from fakepta_tpu.batch import PulsarBatch
    from fakepta_tpu.correlated_noises import optimal_statistic
    from fakepta_tpu.detect import OSSpec
    from fakepta_tpu.parallel.mesh import make_mesh
    from fakepta_tpu.parallel.montecarlo import EnsembleSimulator, GWBConfig

    batch = PulsarBatch.synthetic(npsr=args.npsr, ntoa=args.ntoa,
                                  tspan_years=15.0, toaerr=1e-7,
                                  n_red=30, n_dm=30, seed=0)
    f = np.arange(1, 31) / float(batch.tspan_common)
    psd = np.asarray(spectrum_lib.powerlaw(f, log10_A=args.log10_A,
                                           gamma=13 / 3))
    mesh = make_mesh(jax.devices())
    pos = np.asarray(batch.pos)
    mask = np.asarray(batch.mask, dtype=np.float64)
    counts = mask @ mask.T

    # the device OS lane (fakepta_tpu.detect): per-realization amp2 computed
    # inside the chunk program and packed beside curves/autos — no
    # keep_corr=True, no (R, P, P) fetch, fused Pallas path stays legal.
    # --legacy-host-os keeps the old host path for A/B.
    spec = OSSpec(orf="hd", weighting="noise")
    runs, amp2 = {}, {}
    for name, gwb in (("null", None), ("injected", GWBConfig(psd=psd, orf="hd"))):
        include = ("white", "red", "dm") + (("gwb",) if gwb else ())
        sim = EnsembleSimulator(batch, gwb=gwb, include=include, mesh=mesh)
        out = sim.run(args.nreal, seed=args.seed, chunk=args.chunk,
                      keep_corr=args.legacy_host_os,
                      os=None if args.legacy_host_os else spec)
        runs[name] = matched_filter(out["curves"], out["autos"],
                                    out["bin_centers"])
        if args.legacy_host_os:
            amp2[name] = optimal_statistic(out["corr"], pos,
                                           counts=counts)["amp2"]
        else:
            amp2[name] = out["os"]["stats"]["hd"]["amp2"]

    null, inj = runs["null"], runs["injected"]
    thresh = float(np.percentile(null, 95.0))
    significance = float((inj.mean() - null.mean()) / max(null.std(), 1e-300))
    # the noise-weighted optimal statistic, with sigma calibrated EMPIRICALLY
    # from the matched null ensemble (the analytic white-noise sigma is
    # miscalibrated under red noise; the null run is the yardstick)
    null_os, inj_os = amp2["null"], amp2["injected"]
    sigma_emp = float(np.std(null_os, ddof=1))
    os_significance = float((inj_os.mean() - null_os.mean())
                            / max(sigma_emp, 1e-300))
    print(json.dumps({
        "npsr": args.npsr, "nreal": args.nreal,
        "log10_A": round(args.log10_A, 3),
        "null_mean": float(null.mean()), "null_std": float(null.std()),
        "injected_mean": float(inj.mean()), "injected_std": float(inj.std()),
        "detection_significance_sigma": round(significance, 2),
        "null_95pct_threshold": thresh,
        "detection_rate_at_5pct_false_alarm": round(
            float((inj > thresh).mean()), 3),
        "os_mean_amp2": float(inj_os.mean()),
        "os_null_sigma_empirical": sigma_emp,
        "os_detection_significance_sigma": round(os_significance, 2),
    }))


if __name__ == "__main__":
    main()
