"""Prior-marginalized GWB detection study over device ensembles.

A realistic population question: given per-pulsar noise we only know to within
broad priors, how well does the optimal statistic separate a GWB-injected
array from a null one? The reference cannot ask this at all — every injector
bakes one fixed PSD per call; here `NoiseSampling` redraws the red-noise
hyperparameters of every pulsar (and the GWB amplitude in the injected
ensemble) for every realization inside the compiled device program.

    python examples/population_study.py                    # defaults
    python examples/population_study.py --platform cpu     # no TPU needed
    python examples/population_study.py --cgw              # add a sampled CW
    python examples/population_study.py --scenario ng15    # registry-driven

``--scenario NAME`` sources the array AND the priors from a registered
``fakepta_tpu.scenarios`` entry (docs/SCENARIOS.md) instead of the ad-hoc
flags: the batch comes from ``Scenario.batch_parts()`` (telescope-cadence
TOAs for the survey scenarios, reduced to unit-test scale on CPU), the
red prior from its ``red_draws`` menu when declared, the GWB amplitude
prior brackets its injected ``gwb_log10_A``, and a CW source is sampled
when the scenario declares a CGW population. The printed row then carries
``scenario`` + ``spec_hash`` provenance.

Prints one JSON line: the empirically-calibrated (null-ensemble) detection
statistics under full prior marginalization. The optimal statistic runs on
the device OS lane (``run(os=...)``, ``fakepta_tpu.detect``) — packed beside
curves/autos with no ``keep_corr=True`` and no (R, P, P) fetch;
``--legacy-host-os`` keeps the old host path for A/B.
"""

import argparse
import json
import pathlib
import sys

import numpy as np

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--npsr", type=int, default=40)
    ap.add_argument("--ntoa", type=int, default=260)
    ap.add_argument("--nreal", type=int, default=2000)
    ap.add_argument("--chunk", type=int, default=1000)
    ap.add_argument("--gwb-log10-A", type=float, nargs=2, default=(-14.2, -13.8),
                    help="uniform prior on the injected GWB amplitude")
    ap.add_argument("--red-log10-A", type=float, nargs=2, default=(-15.0, -13.5))
    ap.add_argument("--red-gamma", type=float, nargs=2, default=(1.0, 5.0))
    ap.add_argument("--cgw", action="store_true",
                    help="also sample a continuous-wave source per realization")
    ap.add_argument("--white-prior", action="store_true",
                    help="also marginalize the white-noise dictionary: "
                         "per-pulsar efac ~ U(0.5, 2.5) and log10_tnequad "
                         "~ U(-8, -5) per realization (the reference's "
                         "randomize ranges, as a population prior)")
    ap.add_argument("--red-spectrum", default="powerlaw",
                    choices=["powerlaw", "turnover"],
                    help="red-noise prior family; 'turnover' additionally "
                         "marginalizes the bend frequency lf0 ~ U(-8.8, -8)")
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--scenario", default=None,
                    help="registered fakepta_tpu.scenarios entry: build the "
                         "array and priors from it (reduced to unit-test "
                         "scale on CPU); overrides --npsr/--ntoa and the "
                         "prior flags it declares")
    ap.add_argument("--platform", default=None)
    ap.add_argument("--legacy-host-os", action="store_true",
                    help="A/B path: fetch the full (R, P, P) correlation "
                         "tensors (keep_corr=True) and run the host "
                         "optimal_statistic instead of the device OS lane")
    ap.add_argument("--report", type=pathlib.Path, default=None,
                    help="save the injected ensemble's RunReport (the "
                         "fakepta_tpu.obs JSON-lines telemetry artifact) "
                         "here; inspect with `python -m fakepta_tpu.obs "
                         "summarize PATH` or diff two runs with `compare`")
    args = ap.parse_args()
    import jax
    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    from fakepta_tpu import constants as const
    from fakepta_tpu import spectrum as spectrum_lib
    from fakepta_tpu.batch import PulsarBatch
    from fakepta_tpu.correlated_noises import optimal_statistic
    from fakepta_tpu.detect import OSSpec
    from fakepta_tpu.parallel.mesh import make_mesh
    from fakepta_tpu.parallel.montecarlo import (CGWSampling,
                                                 EnsembleSimulator, GWBConfig,
                                                 NoiseSampling, WhiteSampling)

    scn = scn_toas_abs = None
    if args.scenario:
        from fakepta_tpu.scenarios import registry as scn_registry
        scn = scn_registry.get(args.scenario)
        if jax.devices()[0].platform == "cpu":
            scn = scn.reduced()
        batch, scn_toas_abs, _, _ = scn.batch_parts()
        args.npsr, args.ntoa = batch.t_own.shape
        # prior menu from the spec: amplitude prior brackets the injected
        # background; the red prior is the scenario's declared draw ranges
        args.gwb_log10_A = (scn.gwb_log10_A - 0.2, scn.gwb_log10_A + 0.2)
        if scn.red_draws is not None:
            args.red_log10_A, args.red_gamma = scn.red_draws
        if scn.cgw_population:
            args.cgw = True
        ncomp = scn.gwb_ncomp
    else:
        batch = PulsarBatch.synthetic(npsr=args.npsr, ntoa=args.ntoa,
                                      tspan_years=15.0, toaerr=1e-7,
                                      n_red=30, n_dm=30, seed=0)
        ncomp = 30
    f = np.arange(1, ncomp + 1) / float(batch.tspan_common)
    # the GWBConfig PSD sets the frequency grid; its values are replaced by
    # the per-realization amplitude draws
    psd = np.asarray(spectrum_lib.powerlaw(
        f, log10_A=np.mean(args.gwb_log10_A), gamma=13 / 3))
    mesh = make_mesh(jax.devices())
    pos = np.asarray(batch.pos)
    mask = np.asarray(batch.mask, dtype=np.float64)
    counts = mask @ mask.T

    if args.red_spectrum == "turnover":
        red_prior = NoiseSampling(
            "red", spectrum="turnover",
            params={"log10_A": tuple(args.red_log10_A),
                    "gamma": tuple(args.red_gamma), "lf0": (-8.8, -8.0)})
    else:
        red_prior = NoiseSampling("red", log10_A=tuple(args.red_log10_A),
                                  gamma=tuple(args.red_gamma))
    extra = {}
    if args.white_prior:
        extra.update(white_sample=WhiteSampling(efac=(0.5, 2.5),
                                                log10_tnequad=(-8.0, -5.0)),
                     toaerr2=np.asarray(batch.sigma2))
    if args.cgw:
        if scn_toas_abs is not None:
            toas_abs = np.asarray(scn_toas_abs)  # the scenario's epochs
        else:
            toas_abs = np.tile(
                53000.0 * 86400.0 + np.linspace(0.0, 15 * const.yr,
                                                args.ntoa),
                (args.npsr, 1))
        extra.update(cgw_sample=CGWSampling(tref=float(toas_abs[0].mean())),
                     toas_abs=toas_abs)

    # the device OS lane (fakepta_tpu.detect): amp2 computed inside the chunk
    # program, packed beside curves/autos — no keep_corr, no (R, P, P) fetch
    # (--legacy-host-os keeps the old host path for A/B)
    spec = OSSpec(orf="hd", weighting="noise")
    amp2 = {}
    for name, gwb, samp in (
            ("null", None, [red_prior]),
            ("injected", GWBConfig(psd=psd, orf="hd"),
             [red_prior, NoiseSampling("gwb",
                                       log10_A=tuple(args.gwb_log10_A),
                                       gamma=(13 / 3, 13 / 3))])):
        include = ("white", "red", "dm") + (("gwb",) if gwb else ())
        sim = EnsembleSimulator(batch, gwb=gwb, include=include, mesh=mesh,
                                noise_sample=samp, **extra)
        out = sim.run(args.nreal, seed=args.seed, chunk=args.chunk,
                      keep_corr=args.legacy_host_os,
                      os=None if args.legacy_host_os else spec)
        if args.legacy_host_os:
            amp2[name] = optimal_statistic(out["corr"], pos,
                                           counts=counts)["amp2"]
        else:
            amp2[name] = out["os"]["stats"]["hd"]["amp2"]
        if args.report is not None and name == "injected":
            # the L5 surface: every run carries its telemetry artifact
            out["report"].save(args.report)
            print(f"saved RunReport -> {args.report}", file=sys.stderr)

    null_os = amp2["null"]
    os = {"amp2": amp2["injected"],
          "sigma": float(np.std(null_os, ddof=1))}
    thresh = float(np.percentile(null_os, 95.0))
    print(json.dumps({
        "npsr": args.npsr, "nreal": args.nreal,
        **({"scenario": scn.name, "spec_hash": scn.spec_hash()}
           if scn is not None else {}),
        "gwb_log10_A_prior": list(args.gwb_log10_A),
        # the record a consumer would rebuild the prior from: the actual
        # sampled parameter ranges, not just the CLI echoes
        "red_prior": {"spectrum": args.red_spectrum,
                      **({"log10_A": list(args.red_log10_A),
                          "gamma": list(args.red_gamma)}
                         if args.red_spectrum == "powerlaw" else
                         {k: list(v) for k, v in red_prior.params.items()})},
        "cgw_sampled": bool(args.cgw),
        "white_prior": bool(args.white_prior),
        "red_spectrum": args.red_spectrum,
        "null_amp2_mean": float(null_os.mean()),
        "null_sigma_empirical": float(os["sigma"]),
        "injected_amp2_mean": float(os["amp2"].mean()),
        "detection_significance_sigma": round(
            float((os["amp2"].mean() - null_os.mean()) / os["sigma"]), 2),
        "detection_rate_at_5pct_false_alarm": round(
            float((os["amp2"] > thresh).mean()), 3),
    }))


if __name__ == "__main__":
    main()
