"""CURN free-spectrum posterior via on-device batched MCMC.

The headline workload of ``fakepta_tpu.sample`` (docs/SAMPLING.md): the
model-independent free-spectrum characterization of a common red process —
one ``log10_rho`` amplitude per frequency bin, uniform box priors, nothing
else assumed about the spectrum (the hyper-efficient method of
arxiv 1210.3578; its per-bin conditional structure is embarrassingly
parallel, which is why thousands of device chains eat it for breakfast).

The pipeline is the subsystem end to end: synthesize residuals from an
injected power law, reduce them once to per-pulsar Woodbury moments, fit
the Laplace warm start, then run HMC x parallel-tempering chains entirely
on device — the chain loop is one jitted segment program with zero host
round-trips; thinned draws and R-hat/ESS/acceptance accumulators drain
through the async writer thread. The recovered per-bin posterior should
track the injected power law where the data are informative (low bins) and
relax to the prior where they are not.

    python examples/free_spectrum_posterior.py                  # defaults
    python examples/free_spectrum_posterior.py --nbin 10 --chains 64
    python examples/free_spectrum_posterior.py --out run.jsonl  # obs artifact

Prints one JSON line: per-bin posterior quantiles vs the injected truth,
convergence diagnostics (R-hat, ESS), and throughput. ``--out`` saves the
``fakepta_tpu.sample/1`` artifact that ``python -m fakepta_tpu.obs
summarize``/``compare``/``gate`` consume.
"""

import argparse
import json
import pathlib
import sys

import numpy as np

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="model-independent CURN free-spectrum posterior via "
                    "the on-device batched-MCMC lane")
    parser.add_argument("--npsr", type=int, default=8)
    parser.add_argument("--ntoa", type=int, default=64)
    parser.add_argument("--nbin", type=int, default=4,
                        help="free-spectrum frequency bins (posterior dims)")
    parser.add_argument("--log10-A", type=float, default=-14.5,
                        help="injected CURN power-law amplitude (the "
                             "default keeps the per-bin truth interior to "
                             "the log10_rho box — truth pinned at a prior "
                             "edge piles posterior mass on the boundary "
                             "and costs divergences)")
    parser.add_argument("--gamma", type=float, default=13 / 3,
                        help="injected CURN power-law slope")
    parser.add_argument("--chains", type=int, default=16)
    parser.add_argument("--temps", type=int, default=2)
    parser.add_argument("--steps", type=int, default=400)
    parser.add_argument("--warmup", type=int, default=200)
    parser.add_argument("--thin", type=int, default=2)
    parser.add_argument("--seed", type=int, default=1)
    parser.add_argument("--platform", default=None)
    parser.add_argument("--out", default=None,
                        help="save the obs artifact (JSON-lines) here")
    args = parser.parse_args(argv)

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    from fakepta_tpu import spectrum as spectrum_lib
    from fakepta_tpu.batch import PulsarBatch
    from fakepta_tpu.infer import ComponentSpec, FreeParam, LikelihoodSpec
    from fakepta_tpu.parallel.mesh import make_mesh
    from fakepta_tpu.sample import SampleSpec, SamplingRun

    batch = PulsarBatch.synthetic(npsr=args.npsr, ntoa=args.ntoa,
                                  tspan_years=15.0, toaerr=1e-7,
                                  n_red=args.nbin, n_dm=args.nbin,
                                  red_log10_A=-14.5, dm_log10_A=-14.5,
                                  seed=0)
    # project the injected power law onto the per-bin log10_rho truth
    tspan = float(batch.tspan_common)
    f = np.arange(1, args.nbin + 1) / tspan
    psd = np.asarray(spectrum_lib.powerlaw(
        f, log10_A=args.log10_A, gamma=args.gamma), dtype=float)
    rho_truth = np.clip(0.5 * np.log10(psd / tspan), -8.9, -5.1)

    model = LikelihoodSpec(components=(
        ComponentSpec(target="red", spectrum="batch"),
        ComponentSpec(target="dm", spectrum="batch"),
        ComponentSpec(target="curn", nbin=args.nbin,
                      spectrum="free_spectrum",
                      free=(FreeParam("log10_rho", (-9.0, -5.0),
                                      per_bin=True),)),
    ))
    spec = SampleSpec(model=model, n_chains=args.chains,
                      n_temps=args.temps, thin=args.thin,
                      warmup=args.warmup)
    study = SamplingRun(batch, spec, truth=rho_truth,
                        mesh=make_mesh(jax.devices()), data_seed=args.seed)
    out = study.run(args.steps, seed=args.seed, pipeline_depth=2)

    draws = out["theta"].reshape(-1, args.nbin)     # (S*K, nbin)
    q = np.percentile(draws, [5, 50, 95], axis=0)
    row = {
        "npsr": args.npsr, "nbin": args.nbin, "chains": args.chains,
        "temps": args.temps, "steps": args.steps,
        "rho_truth": np.round(rho_truth, 3).tolist(),
        "rho_q05": np.round(q[0], 3).tolist(),
        "rho_median": np.round(q[1], 3).tolist(),
        "rho_q95": np.round(q[2], 3).tolist(),
        # fraction of bins whose 90% interval covers the injected truth
        "truth_coverage": float(np.mean(
            (rho_truth >= q[0]) & (rho_truth <= q[2]))),
        **out["summary"],
    }
    if args.out:
        row["artifact"] = study.save(args.out)
    print(json.dumps(row))
    return 0


if __name__ == "__main__":
    sys.exit(main())
