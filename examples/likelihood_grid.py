"""CURN amplitude-slope likelihood grid over Monte-Carlo ensembles.

The analysis the engine's simulations exist to feed: for every realization,
evaluate the GP-marginalized PTA log-likelihood on a (log10_A, gamma) grid
of the common-process hyperparameters and ask how often the
maximum-likelihood grid point recovers the injected truth. The device path
runs the whole grid INSIDE the jitted chunk program
(``EnsembleSimulator.run(lnlike=...)``, ``fakepta_tpu.infer``): Woodbury
rank-2N solves, no residual fetch, no host sampler.

``--legacy-host`` is the A/B flag: it runs the reference's own analysis
route instead — per-pulsar dense ``n_toa x n_toa`` covariances with
``np.linalg`` solves per grid point (the ``fake_pta.py:515-524`` / SURVEY §E
pattern), on host-simulated realizations of the same model — and reports
the same recovery metrics plus wall time, so the two pipelines' answers and
costs are directly comparable:

    python examples/likelihood_grid.py                   # device lane
    python examples/likelihood_grid.py --legacy-host     # dense host A/B
    python examples/likelihood_grid.py --npsr 100 --ntoa 780 --nreal 10000

Prints one JSON line with the grid, recovery metrics and timing.
"""

import argparse
import json
import pathlib
import sys
import time

import numpy as np

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


def run_device(args, batch, psd, model, truth):
    import jax

    from fakepta_tpu.infer import InferenceRun
    from fakepta_tpu.parallel.mesh import make_mesh
    from fakepta_tpu.parallel.montecarlo import GWBConfig

    study = InferenceRun(
        batch, model, gwb=GWBConfig(psd=psd, orf="curn"),
        grid_shape=tuple(args.grid), truth=truth,
        include=("white", "red", "dm", "gwb"),
        mesh=make_mesh(jax.devices()))
    t0 = time.perf_counter()
    out = study.run(args.nreal, seed=args.seed, chunk=args.chunk)
    return out["summary"], time.perf_counter() - t0


def run_legacy_host(args, batch, psd, model, truth):
    """The reference's dense-covariance analysis route, as the A/B baseline.

    Simulates each realization and evaluates the grid per pulsar through the
    full n_toa^3 path: C_k = N + T Phi_k T^T built dense, lnL via
    slogdet + solve — what `fakepta_tpu.infer` replaces with rank-2N
    Woodbury solves on device.
    """
    import jax.numpy as jnp

    from fakepta_tpu.infer import build, theta_grid

    compiled = build(model, batch)
    theta = theta_grid(model, tuple(args.grid))
    tmat = np.asarray(compiled.basis(batch), dtype=np.float64)
    sigma2 = np.asarray(batch.sigma2, dtype=np.float64)
    npsr, ntoa = sigma2.shape
    ln2pi = np.log(2.0 * np.pi)

    # dense per-(pulsar, grid-point) covariances of the model
    phis = [np.asarray(compiled.phi(jnp.asarray(t), batch),
                       dtype=np.float64) for t in theta]
    phi_true = np.asarray(
        compiled.phi(jnp.asarray(np.asarray(truth)), batch),
        dtype=np.float64)

    rng = np.random.default_rng(args.seed)
    chols_true = [np.linalg.cholesky(
        np.diag(sigma2[p]) + (tmat[p] * phi_true[p]) @ tmat[p].T)
        for p in range(npsr)]

    t0 = time.perf_counter()
    factors = []
    for k in range(theta.shape[0]):
        per_psr = []
        for p in range(npsr):
            C = np.diag(sigma2[p]) + (tmat[p] * phis[k][p]) @ tmat[p].T
            chol = np.linalg.cholesky(C)
            per_psr.append((chol, 2.0 * np.log(np.diag(chol)).sum()))
        factors.append(per_psr)
    lnl = np.zeros((args.nreal, theta.shape[0]))
    for r in range(args.nreal):
        res = [chols_true[p] @ rng.standard_normal(ntoa)
               for p in range(npsr)]
        for k, per_psr in enumerate(factors):
            total = 0.0
            for p, (chol, ld) in enumerate(per_psr):
                y = np.linalg.solve(chol, res[p])
                total += -0.5 * (y @ y + ld + ntoa * ln2pi)
            lnl[r, k] = total
    wall = time.perf_counter() - t0

    span = np.maximum(theta.max(axis=0) - theta.min(axis=0), 1e-300)
    z = (theta - np.asarray(truth)[None]) / span[None]
    truth_idx = int(np.argmin((z ** 2).sum(axis=1)))
    map_idx = np.argmax(lnl, axis=1)
    dist = np.sqrt((z[map_idx] ** 2).sum(axis=1))
    summary = {
        "lnlike_grid_k": int(theta.shape[0]),
        "lnlike_lnl_max_mean": float(lnl.max(axis=1).mean()),
        "lnlike_map_hit_rate": round(
            float((map_idx == truth_idx).mean()), 4),
        "lnlike_map_l2_mean": round(float(dist.mean()), 6),
    }
    return summary, wall


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--npsr", type=int, default=20)
    ap.add_argument("--ntoa", type=int, default=260)
    ap.add_argument("--nreal", type=int, default=1000)
    ap.add_argument("--chunk", type=int, default=250)
    ap.add_argument("--log10-A", type=float, default=-13.2,
                    help="injected CURN amplitude (the grid truth)")
    ap.add_argument("--gamma", type=float, default=13 / 3)
    ap.add_argument("--grid", type=int, nargs=2, default=[5, 5],
                    metavar=("NA", "NG"))
    ap.add_argument("--ncomp", type=int, default=10,
                    help="common-process Fourier components")
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--platform", default=None,
                    help="force a jax platform (e.g. cpu)")
    ap.add_argument("--legacy-host", action="store_true",
                    help="A/B path: the reference's dense n_toa^3 "
                         "covariance grid on host-simulated realizations "
                         "instead of the device Woodbury lane")
    args = ap.parse_args()
    import jax
    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    from fakepta_tpu import spectrum as spectrum_lib
    from fakepta_tpu.batch import PulsarBatch
    from fakepta_tpu.infer import ComponentSpec, FreeParam, LikelihoodSpec

    # quiet per-pulsar noise so the common-process truth dominates the grid
    batch = PulsarBatch.synthetic(npsr=args.npsr, ntoa=args.ntoa,
                                  tspan_years=15.0, toaerr=1e-7,
                                  n_red=10, n_dm=10, red_log10_A=-14.5,
                                  dm_log10_A=-14.5, seed=0)
    f = np.arange(1, args.ncomp + 1) / float(batch.tspan_common)
    psd = np.asarray(spectrum_lib.powerlaw(f, log10_A=args.log10_A,
                                           gamma=args.gamma))
    model = LikelihoodSpec(components=(
        ComponentSpec(target="red", spectrum="batch"),
        ComponentSpec(target="dm", spectrum="batch"),
        ComponentSpec(target="curn", nbin=args.ncomp, free=(
            FreeParam("log10_A", (args.log10_A - 0.6, args.log10_A + 0.6)),
            FreeParam("gamma", (2.0, 6.0)))),
    ))
    truth = (args.log10_A, args.gamma)
    if args.legacy_host:
        summary, wall = run_legacy_host(args, batch, psd, model, truth)
    else:
        summary, wall = run_device(args, batch, psd, model, truth)
    print(json.dumps({
        "npsr": args.npsr, "ntoa": args.ntoa, "nreal": args.nreal,
        "log10_A": round(args.log10_A, 3), "gamma": round(args.gamma, 3),
        "grid": list(args.grid),
        "legacy_host": bool(args.legacy_host),
        "wall_s": round(wall, 3),
        "grid_evals_per_s": round(
            args.nreal * summary["lnlike_grid_k"] / max(wall, 1e-9), 1),
        **summary,
    }))


if __name__ == "__main__":
    main()
