"""End-to-end simulation workflow (parity: reference ``examples/make_fake_array.py``).

Builds a fake pulsar array, wipes it to an ideal (noise-free) state, re-injects
every per-pulsar noise process from a noisedict, injects an HD-correlated
stochastic GW background and a continuous-wave source, then pickles the array in
the ENTERPRISE-compatible layout.

Unlike the reference script — which hardcodes the author's absolute paths and
cannot run as shipped — this one is fully seeded and self-contained:

    python examples/make_fake_array.py                 # 25-pulsar default run
    python examples/make_fake_array.py --npsrs 4 --ntoas 100   # quick smoke

The shipped ``simulated_data/noisedict_example.json`` and
``simulated_data/custom_models_example.json`` follow the ENTERPRISE naming
contract (SURVEY.md §2.4) and match the pulsar names produced by
``make_fake_array(npsrs=8, seed=1234)`` so the copy-array replay path can be
exercised without any external dataset.
"""

import argparse
import json
import pickle
from pathlib import Path

from fakepta_tpu.correlated_noises import add_common_correlated_noise
from fakepta_tpu.fake_pta import copy_array, make_fake_array, plot_pta

HERE = Path(__file__).resolve().parent
DATA = HERE / "simulated_data"


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--npsrs", type=int, default=25)
    ap.add_argument("--Tobs", type=float, default=10.0, help="years")
    ap.add_argument("--ntoas", type=int, default=1000)
    ap.add_argument("--toaerr", type=float, default=1e-6, help="seconds")
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument("--replay", action="store_true",
                    help="exercise the copy_array replay path with the shipped "
                         "example noisedict/custom_models (8-pulsar array)")
    ap.add_argument("--plot", action="store_true", help="show the sky map")
    ap.add_argument("--out", type=Path, default=None)
    ap.add_argument("--platform", default=None,
                    help="force a jax platform, e.g. 'cpu' (backends initialize "
                         "lazily, so this works even after the imports above)")
    args = ap.parse_args()

    if args.platform:
        import jax
        jax.config.update("jax_platforms", args.platform)

    if args.replay:
        # The bridge for replaying real datasets: rebuild the seeded example
        # array, then clone it while re-resolving the shipped noisedict —
        # exactly the EPTA-DR2 workflow of the reference script. The source
        # array's parameters are pinned: the shipped JSONs name its pulsars
        # and backends, which the seed makes reproducible.
        noisedict = json.loads((DATA / "noisedict_example.json").read_text())
        custom_models = json.loads((DATA / "custom_models_example.json").read_text())
        psrs_0 = make_fake_array(npsrs=8, Tobs=10.0, ntoas=100,
                                 isotropic=True, toaerr=1e-6, seed=1234)
        psrs = copy_array(psrs_0, noisedict, custom_models, seed=args.seed)
    else:
        psrs = make_fake_array(npsrs=args.npsrs, Tobs=args.Tobs, ntoas=args.ntoas,
                               isotropic=True, gaps=True, toaerr=args.toaerr,
                               pdist=1.0, backends=["NUPPI"], seed=args.seed)

    # Set residuals to zero and re-inject every noise process. In the replay
    # path the GP hyper-parameters come from the noisedict; in the fresh path
    # make_ideal() forgot the randomized ones, so pass them explicitly (the
    # reference would silently skip injection here — we raise instead).
    gp_kwargs = {} if args.replay else dict(log10_A=-14.0, gamma=3.0)
    for psr in psrs:
        print("Injecting noises for", psr.name)
        psr.make_ideal()
        psr.add_white_noise()
        psr.add_red_noise(**gp_kwargs)
        psr.add_dm_noise(**gp_kwargs)
        psr.add_chromatic_noise(**gp_kwargs)

    print("Injecting GWB")
    add_common_correlated_noise(psrs, log10_A=-15.0, gamma=13 / 3, orf="hd",
                                seed=args.seed)

    print("Injecting CGW")
    cgw = dict(costheta=0.12, phi=3.2, cosinc=0.3, log10_mc=9.2, log10_fgw=-8.3,
               log10_h=-13.5, phase0=1.6, psi=1.2)
    for psr in psrs:
        psr.add_cgw(psrterm=True, **cgw)

    if args.plot:
        plot_pta(psrs, plot_name=False)

    out = args.out or DATA / f"fake_{len(psrs)}_psrs_gwb+cgw.pkl"
    out.parent.mkdir(parents=True, exist_ok=True)
    with open(out, "wb") as fh:
        pickle.dump(psrs, fh)
    print("Done —", out)


if __name__ == "__main__":
    main()
