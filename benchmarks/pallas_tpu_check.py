"""Compile-and-validate the fused Pallas kernel on the real TPU (VERDICT r2 #4).

Runs the fused statistic path NON-interpreted (a real Mosaic kernel):
1. parity vs the XLA path at a small size, both precisions;
2. compile + run at the FLAGSHIP size (100 psr, 780 TOAs) where the VMEM-capped
   realization tile matters (pick_rt returns 4 there);
3. throughput: XLA vs fused at the flagship size.

Prints one JSON line per check. Exits non-zero on any parity failure.
"""

import json
import sys
import time

import numpy as np


def main():
    import jax

    from fakepta_tpu import spectrum as spectrum_lib
    from fakepta_tpu.batch import PulsarBatch
    from fakepta_tpu.parallel.mesh import make_mesh
    from fakepta_tpu.parallel.montecarlo import EnsembleSimulator, GWBConfig

    if jax.devices()[0].platform != "tpu":
        raise SystemExit("this check needs the real TPU (interpret-mode parity "
                         "is already covered by the test suite)")
    mesh = make_mesh(jax.devices())
    ok = True

    def gwb(batch, ncomp=8, log10_A=-13.5):
        f = np.arange(1, ncomp + 1) / float(batch.tspan_common)
        return GWBConfig(psd=np.asarray(spectrum_lib.powerlaw(
            f, log10_A=log10_A, gamma=13 / 3)), orf="hd")

    # 1. small-size parity, real Mosaic kernel
    small = PulsarBatch.synthetic(npsr=8, ntoa=64, tspan_years=10.0,
                                  toaerr=1e-7, n_red=4, n_dm=4, seed=1)
    ref = EnsembleSimulator(small, gwb=gwb(small), mesh=mesh,
                            use_pallas=False).run(8, seed=3, chunk=8)
    for prec, atol_scale in (("bf16", 1e-2), ("f32", 1e-5)):
        out = EnsembleSimulator(small, gwb=gwb(small), mesh=mesh,
                                use_pallas=True, pallas_precision=prec
                                ).run(8, seed=3, chunk=8)
        scale = float(np.abs(ref["curves"]).max())
        err = float(np.abs(out["curves"] - ref["curves"]).max())
        passed = bool(err <= atol_scale * scale
                      and np.allclose(out["autos"], ref["autos"],
                                      rtol=atol_scale))
        ok &= passed
        print(json.dumps({"check": f"parity_{prec}_mosaic", "passed": passed,
                          "max_err": err, "scale": scale}))

    # 2 + 3. flagship size: compile under the VMEM cap, throughput both paths.
    # Skipped when parity already failed: benchmarking a kernel that produces
    # wrong answers would publish meaningless speedup numbers.
    if not ok:
        print(json.dumps({"check": "flagship", "skipped": "parity failed"}))
        sys.exit(1)
    flag = PulsarBatch.synthetic(npsr=100, ntoa=780, tspan_years=15.0,
                                 toaerr=1e-7, n_red=30, n_dm=100, seed=0)
    cfg = gwb(flag, ncomp=30, log10_A=np.log10(2e-15))
    nreal, chunk = 10_000, 10_000
    results = {}
    for name, kw in (("xla", dict(use_pallas=False)),
                     ("pallas_bf16", dict(use_pallas=True,
                                          pallas_precision="bf16"))):
        sim = EnsembleSimulator(flag, gwb=cfg, mesh=mesh, **kw)
        sim.run(chunk, seed=9, chunk=chunk)          # compile + warm
        t0 = time.perf_counter()
        out = sim.run(nreal, seed=1, chunk=chunk)
        t = time.perf_counter() - t0
        if not np.all(np.isfinite(out["curves"])):
            print(json.dumps({"check": f"flagship_{name}",
                              "passed": False, "reason": "non-finite output"}))
            sys.exit(1)
        results[name] = nreal / t / len(jax.devices())
        print(json.dumps({"check": f"flagship_{name}",
                          "real_per_s_per_chip": round(results[name], 2)}))
    print(json.dumps({"check": "flagship_speedup_fused_vs_xla",
                      "ratio": round(results["pallas_bf16"] / results["xla"],
                                     3)}))
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
