"""Compile-and-validate the fused Pallas kernel on the real TPU (VERDICT r2 #4).

Runs the fused statistic paths NON-interpreted (real Mosaic kernels):
1. parity vs the XLA path at a small size, both precisions — the binned-
   correlation kernel AND the whole-chunk megakernel (use_pallas='mega',
   f32 and the run(precision='bf16') storage mode);
2. compile + run at the FLAGSHIP size (100 psr, 780 TOAs) where the VMEM-capped
   realization tile matters (pick_rt returns 4 there);
3. throughput: XLA vs fused vs megakernel at the flagship size.

(The interpret-mode lane in tests/test_megakernel.py covers kernel
correctness without hardware; this script is the on-TPU Mosaic check.)

Prints one JSON line per check. Exits non-zero on any parity failure.
"""

import json
import pathlib
import sys
import time

import numpy as np

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


def main():
    import jax
    import jax.numpy as jnp

    from fakepta_tpu import spectrum as spectrum_lib
    from fakepta_tpu.batch import PulsarBatch
    from fakepta_tpu.parallel.mesh import make_mesh
    from fakepta_tpu.parallel.montecarlo import EnsembleSimulator, GWBConfig

    if jax.devices()[0].platform != "tpu":
        raise SystemExit("this check needs the real TPU (interpret-mode parity "
                         "is already covered by the test suite)")
    mesh = make_mesh(jax.devices())
    ok = True

    def gwb(batch, ncomp=8, log10_A=-13.5):
        f = np.arange(1, ncomp + 1) / float(batch.tspan_common)
        return GWBConfig(psd=np.asarray(spectrum_lib.powerlaw(
            f, log10_A=log10_A, gamma=13 / 3)), orf="hd")

    # 1a. kernel-level parity vs a float64 numpy oracle (real Mosaic compile).
    # This isolates the statistic kernel: f32 mode (Precision.HIGHEST) must hit
    # ~1e-5 relative, bf16 mode (operand rounding, 8 mantissa bits) ~1e-2.
    # An end-to-end XLA-vs-Pallas comparison can NOT test f32 at 1e-5 because
    # the residual *generation* matmuls run at XLA's default TPU precision
    # (f32 operands rounded to bf16), injecting ~1e-3 of its own.
    from fakepta_tpu.ops.pallas_kernels import binned_correlation, pick_rt

    rng = np.random.default_rng(7)
    R, PLOC, PFULL, T, NB = 8, 8, 8, 64, 9
    res_l = rng.standard_normal((R, PLOC, T)).astype(np.float32) * 1e-6
    res_f = rng.standard_normal((R, PFULL, T)).astype(np.float32) * 1e-6
    w = rng.standard_normal((NB + 1, PLOC, PFULL)).astype(np.float32)
    corr64 = np.einsum("rpt,rqt->rpq", res_l.astype(np.float64),
                       res_f.astype(np.float64))
    want = np.einsum("rpq,npq->rn", corr64, w.astype(np.float64))
    # rt=4 exercises the sublane-padded (1, rt, LANES) output layout the
    # flagship's VMEM cap forces (pick_rt returns 4 there); rt=8 the aligned
    # one. An indexing bug specific to rt<8 would otherwise reach the flagship
    # stage checked only for finiteness.
    # not a bare assert: stripped (-O) runs must still catch pick_rt drift
    if pick_rt(R, PLOC, PFULL, T, NB) != 8:
        raise SystemExit("small-size pick_rt drifted; rt=8 lane no longer "
                         "covers the aligned layout")
    for mxu in (False, True):
        for rt in (4, 8):
            for prec, tol in (("bf16", 1e-2), ("f32", 1e-5)):
                curves, autos = binned_correlation(
                    jnp.asarray(res_l), jnp.asarray(res_f), jnp.asarray(w),
                    nbins=NB, rt=rt, precision=prec, mxu_binning=mxu)
                got = np.concatenate([np.asarray(curves),
                                      np.asarray(autos)[:, None]], axis=1)
                scale = float(np.abs(want).max())
                err = float(np.abs(got - want).max())
                passed = bool(err <= tol * scale)
                ok &= passed
                tag = "mxu" if mxu else "vpu"
                print(json.dumps(
                    {"check": f"kernel_parity_{prec}_rt{rt}_{tag}_mosaic",
                     "passed": passed, "max_rel_err": err / scale}))

    # 1b. end-to-end simulator parity, XLA vs fused, at the generation-path
    # tolerance (default-precision matmuls bound both runs at ~bf16 rounding).
    small = PulsarBatch.synthetic(npsr=8, ntoa=64, tspan_years=10.0,
                                  toaerr=1e-7, n_red=4, n_dm=4, seed=1)
    ref = EnsembleSimulator(small, gwb=gwb(small), mesh=mesh,
                            use_pallas=False).run(8, seed=3, chunk=8)
    for prec in ("bf16", "f32"):
        out = EnsembleSimulator(small, gwb=gwb(small), mesh=mesh,
                                use_pallas=True, pallas_precision=prec
                                ).run(8, seed=3, chunk=8)
        scale = float(np.abs(ref["curves"]).max())
        err = float(np.abs(out["curves"] - ref["curves"]).max())
        passed = bool(err <= 1e-2 * scale
                      and np.allclose(out["autos"], ref["autos"], rtol=1e-2))
        ok &= passed
        print(json.dumps({"check": f"e2e_parity_{prec}_mosaic", "passed": passed,
                          "max_err": err, "scale": scale}))

    # 1c. the whole-chunk megakernel (ops/megakernel.py), f32 and the
    # bf16-storage run mode — in-kernel basis recompute + residual assembly
    # as a real Mosaic program
    sim_mega = EnsembleSimulator(small, gwb=gwb(small), mesh=mesh,
                                 use_pallas="mega")
    for prec, tol in ((None, 1e-3), ("bf16", 1e-2)):
        out = sim_mega.run(8, seed=3, chunk=8, precision=prec)
        scale = float(np.abs(ref["curves"]).max())
        err = float(np.abs(out["curves"] - ref["curves"]).max())
        passed = bool(err <= tol * scale)
        ok &= passed
        print(json.dumps({"check": f"e2e_parity_mega_{prec or 'f32'}_mosaic",
                          "passed": passed, "max_err": err, "scale": scale}))

    # 2 + 3. flagship size: compile under the VMEM cap, throughput both paths.
    # Skipped when parity already failed: benchmarking a kernel that produces
    # wrong answers would publish meaningless speedup numbers.
    if not ok:
        print(json.dumps({"check": "flagship", "skipped": "parity failed"}))
        sys.exit(1)
    from fakepta_tpu.scenarios.registry import flagship_batch
    flag = flagship_batch()
    cfg = gwb(flag, ncomp=30, log10_A=np.log10(2e-15))
    nreal, chunk = 10_000, 10_000
    results = {}
    for name, kw, rkw in (
            ("xla", dict(use_pallas=False), {}),
            ("pallas_bf16_vpu", dict(use_pallas=True,
                                     pallas_precision="bf16",
                                     pallas_mxu_binning=False), {}),
            ("pallas_bf16_mxu", dict(use_pallas=True,
                                     pallas_precision="bf16",
                                     pallas_mxu_binning=True), {}),
            ("mega_f32", dict(use_pallas="mega"), {}),
            ("mega_bf16", dict(use_pallas="mega"),
             dict(precision="bf16"))):
        sim = EnsembleSimulator(flag, gwb=cfg, mesh=mesh, **kw)
        sim.run(chunk, seed=9, chunk=chunk, **rkw)   # compile + warm
        t0 = time.perf_counter()
        out = sim.run(nreal, seed=1, chunk=chunk, **rkw)
        t = time.perf_counter() - t0
        if not np.all(np.isfinite(out["curves"])):
            print(json.dumps({"check": f"flagship_{name}",
                              "passed": False, "reason": "non-finite output"}))
            sys.exit(1)
        results[name] = nreal / t / len(jax.devices())
        print(json.dumps({"check": f"flagship_{name}",
                          "real_per_s_per_chip": round(results[name], 2)}))
    print(json.dumps({"check": "flagship_speedup_fused_vs_xla",
                      "vpu_binning": round(results["pallas_bf16_vpu"]
                                           / results["xla"], 3),
                      "mxu_binning": round(results["pallas_bf16_mxu"]
                                           / results["xla"], 3),
                      "mega_f32": round(results["mega_f32"]
                                        / results["xla"], 3),
                      "mega_bf16": round(results["mega_bf16"]
                                         / results["xla"], 3)}))
    if "--crossover" in sys.argv:
        crossover(mesh, gwb)
    sys.exit(0)


def crossover(mesh, gwb):
    """HBM-lean crossover sweep (VERDICT r3 weak #2): find the pulsar count
    where the fused kernel overtakes XLA.

    The XLA path materializes the (chunk, P, P) correlation tensor in HBM, so
    its chunk must SHRINK as P grows (fixed ~4 GB correlation budget here);
    the fused path keeps each block in VMEM and holds its chunk. Prints one
    JSON line per (P, path) with the chunk used and realizations/s/chip.
    """
    import jax

    from fakepta_tpu.batch import PulsarBatch
    from fakepta_tpu.parallel.montecarlo import EnsembleSimulator

    corr_budget = 4 << 30
    for npsr in (100, 200, 400, 600):
        batch = PulsarBatch.synthetic(npsr=npsr, ntoa=780, tspan_years=15.0,
                                      toaerr=1e-7, n_red=30, n_dm=100, seed=0)
        cfg = gwb(batch, ncomp=30, log10_A=np.log10(2e-15))
        chunk_xla = max(512, min(10_000, corr_budget // (npsr * npsr * 4)))
        chunk_xla -= chunk_xla % 8
        for name, chunk, kw in (
                ("xla", chunk_xla, dict(use_pallas=False)),
                ("pallas_bf16_mxu", 10_000, dict(use_pallas=True,
                                                 pallas_precision="bf16",
                                                 pallas_mxu_binning=True))):
            try:
                sim = EnsembleSimulator(batch, gwb=cfg, mesh=mesh, **kw)
                nreal = 2 * chunk
                sim.run(chunk, seed=9, chunk=chunk)
                t0 = time.perf_counter()
                sim.run(nreal, seed=1, chunk=chunk)
                t = time.perf_counter() - t0
                rate = nreal / t / len(jax.devices())
                print(json.dumps({"check": "crossover", "npsr": npsr,
                                  "path": name, "chunk": chunk,
                                  "real_per_s_per_chip": round(rate, 2)}))
            except Exception as e:    # OOM at large P is itself a datapoint
                print(json.dumps({"check": "crossover", "npsr": npsr,
                                  "path": name, "chunk": chunk,
                                  "error": str(e)[:200]}))


if __name__ == "__main__":
    main()
