"""Benchmark suite for the BASELINE.md configs (1-5 from BASELINE.json, plus
6: config 4 as one device program, 7: the full-noise ECORR/system ensemble,
8: the flagship with per-realization hyperparameter sampling, 9: the flagship
with a per-realization sampled CW source, 10: the 256-pulsar scale-out,
11: the flagship with per-realization white-noise sampling, 12: the chaos
lane, 13: the multi-replica serve fleet A/B with mid-load replica kill,
14: the streaming-ingestion A/B — single-epoch incremental append vs full
restage, docs/STREAMING.md, 15: the elastic chaos lane, 16: the multi-tenant
gateway lane, 17: the scenario golden smoke — the ``fakepta_tpu.scenarios``
golden-run harness as a first-class config, 18: the factorized
free-spectrum A/B — per-bin lanes vs the joint sampler plus the
O(bins-touched) streaming refresh, f64-oracle-gated, docs/SAMPLING.md).

``--scenario NAME`` points the chaos lanes (12, 15) and the golden smoke
(17) at a registered scenario from ``fakepta_tpu.scenarios`` instead of
their ad-hoc arrays; their rows then carry a ``scenario`` column (part of
the ``obs`` row identity — ``obs gate`` only bands same-scenario
same-platform rows, docs/SCENARIOS.md).

Prints one JSON line per config. The reference publishes no numbers
(SURVEY.md §6), so these are the framework's own measured results; run with
``--update-baseline`` to append a measured table to BASELINE.md. Ensemble
rows carry the ``fakepta_tpu.obs`` telemetry fields (``compile_s``,
``steady_real_per_s_per_chip``, ``retraces``, ``cost_bytes_per_chunk``,
``peak_hbm_bytes`` — see the bench.py docstring for the schema), sourced
from the RunReport each ``sim.run()`` attaches. The flagship row (config 5) additionally carries the
detection-lane figures ``os_real_per_s_per_chip`` / ``os_bytes_per_chunk``
from a second measured run with ``os='hd'`` (the device optimal statistic,
``fakepta_tpu.detect``) and the inference-lane figures
``lnlike_evals_per_s_per_chip`` / ``lnlike_bytes_per_chunk`` from a third
measured run with a K=16 CURN hyperparameter grid (the GP-marginalized
device likelihood, ``fakepta_tpu.infer``) and the sampling-lane figures
``ess_per_s_per_chip`` / ``sample_steps_per_s_per_chip`` / ``rhat_max`` /
``accept_rate`` from an on-device batched-MCMC free-spectrum posterior
(``fakepta_tpu.sample``, docs/SAMPLING.md) and the serving-lane figures
``serve_qps_per_chip`` / ``serve_p50_ms`` / ``serve_p99_ms`` /
``coalesce_factor`` / ``serve_speedup_x`` from the built-in synthetic load
generator over the warm-pool scheduler (``fakepta_tpu.serve``,
docs/SERVING.md) and the autotuner lane's ``tuned`` / ``tune_probe_s`` /
``tuned_real_per_s_per_chip`` / ``tuned_speedup_x`` A/B
(``fakepta_tpu.tune``, docs/TUNING.md — see the bench.py docstring for
the full schema). Every row's ``platform`` column reads
``tune.fingerprint()``, the same single source ``obs gate`` bands rows
with.

    python benchmarks/suite.py                 # all configs, default sizes
    python benchmarks/suite.py --configs 1 2   # subset
    python benchmarks/suite.py --platform cpu  # force a jax platform
"""

import argparse
import json
import sys
import time
from datetime import date
from pathlib import Path

import numpy as np

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))


def _flagship_toas_abs(batch):
    """(npsr, ntoa) absolute MJD-second epochs matching a synthetic batch's
    uniform-cadence grid (span derived from the batch, not re-hardcoded)."""
    npsr, ntoa = batch.t_own.shape
    span = float(batch.tspan_common)
    return np.tile(53000.0 * 86400.0 + np.linspace(0.0, span, ntoa), (npsr, 1))



# global measurement-protocol scale (set by --nreal-scale): CPU stand-in runs
# shrink the realization counts 10x so a full labeled sweep stays tractable;
# rates are steady-state per chunk, so the scaled protocol measures the same
# quantity with more timer noise. Rows carry the scale so BASELINE.md entries
# are self-describing.
_NREAL_SCALE = 1.0

# --scenario NAME: the chaos lanes (12, 15) and the golden smoke (17) run
# against this registered scenario (fakepta_tpu.scenarios) instead of their
# ad-hoc arrays; None keeps the historical configs byte-for-byte
_SCENARIO = None


def _scenario():
    """The ``--scenario`` selection, reduced to the platform's scale
    (CPU stand-ins run the deterministic ``Scenario.reduced()`` variant —
    same spec family, unit-test sizes), or None when unset."""
    if _SCENARIO is None:
        return None
    import jax

    from fakepta_tpu.scenarios import registry as scn_registry
    scn = scn_registry.get(_SCENARIO)
    if jax.devices()[0].platform == "cpu":
        scn = scn.reduced()
    return scn


def _scaled(nreal, chunk):
    n = max(chunk, int(round(nreal * _NREAL_SCALE)))
    n -= n % chunk
    return max(n, chunk), chunk

def _hd_psd(batch, ncomp=30):
    """The standard HD-background PSD (A=2e-15, gamma=13/3) on the batch's
    common grid — the config every ensemble benchmark injects."""
    from fakepta_tpu import spectrum as spectrum_lib
    f = np.arange(1, ncomp + 1) / float(batch.tspan_common)
    return np.asarray(spectrum_lib.powerlaw(f, log10_A=np.log10(2e-15),
                                            gamma=13 / 3))


def _ensemble_rate(sim, nreal, chunk):
    """Warm (compile) one chunk, then measure steady-state realizations/s.

    Returns ``(rate, obs_fields)``: the end-to-end rate plus the
    ``fakepta_tpu.obs`` RunReport fields every ensemble row carries
    (``compile_s`` from the warm-up run, ``steady_real_per_s_per_chip`` /
    ``retraces`` / ``cost_bytes_per_chunk`` from the measured run — the
    bench.py line schema, so BENCH/BASELINE rows stay self-describing).
    """
    warm = sim.run(chunk, seed=9, chunk=chunk)
    t0 = time.perf_counter()
    out = sim.run(nreal, seed=1, chunk=chunk)
    rate = nreal / (time.perf_counter() - t0)
    rep = out["report"]
    rep_sum = rep.summary()
    fields = {
        "compile_s": round(warm["report"].compile_s, 3),
        "steady_real_per_s_per_chip": round(
            rep.steady_real_per_s_per_chip(), 2),
        "retraces": rep.retraces,
        # async chunk-pipeline overlap figures (bench.py docstring schema:
        # executed depth, host time the dispatch loop waited on, checkpoint
        # append time — both timings lower-is-better under `obs compare`)
        "pipeline_depth": rep_sum.get("pipeline_depth", 0),
        "pipeline_stall_s": rep_sum.get("pipeline_stall_s", 0.0),
        "ckpt_wait_s": rep_sum.get("ckpt_wait_s", 0.0),
    }
    # chunk cost + roofline placement (bench.py docstring schema: measured
    # bytes, the analytic HBM model, and the intensity — higher-is-better)
    # plus the memwatch HBM watermark (peak_hbm_bytes, lower-is-better)
    for key in ("cost_bytes_per_chunk", "model_bytes_per_chunk",
                "intensity_flop_per_byte", "peak_hbm_bytes"):
        if rep_sum.get(key):
            fields[key] = rep_sum[key]
    return rate, fields


def _timeit(fn, repeats=3):
    fn()                                   # warm (compile)
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def config1():
    """1 pulsar, 10 yr weekly TOAs, white noise only (ref fake_pta.py:201-230)."""
    from fakepta_tpu import constants as const
    from fakepta_tpu.fake_pta import Pulsar

    psr = Pulsar(np.linspace(0, 10 * const.yr, 520), 1e-6, 1.0, 1.0, seed=0)
    t = _timeit(lambda: psr.add_white_noise(seed=1))
    return {"config": 1, "metric": "white-noise injections/s (1 psr, 520 TOAs)",
            "value": round(1 / t, 1), "unit": "inj/s"}


def config2():
    """10-pulsar array, per-pulsar power-law red noise (ref :258-281,357-387).

    Measured through the array-level injector (one batched kernel) — the
    framework's intended path for the same task the reference performs with a
    Python loop; per-pulsar draws stay independent (seed folds by index).
    """
    from fakepta_tpu import constants as const
    from fakepta_tpu.fake_pta import Pulsar, add_noise_array

    psrs = [Pulsar(np.linspace(0, 10 * const.yr, 520), 1e-6,
                   1.0 + 0.1 * k, 0.3 * k, seed=k) for k in range(10)]

    t = _timeit(lambda: add_noise_array(
        psrs, signal="red_noise", spectrum="powerlaw", log10_A=-14.0,
        gamma=13 / 3, seed=2))
    return {"config": 2, "metric": "red-noise injections/s (10 psr, 30 bins)",
            "value": round(10 / t, 1), "unit": "inj/s"}


def config3():
    """45-pulsar HD-correlated GWB injection (ref correlated_noises.py:111-160)."""
    from fakepta_tpu import constants as const
    from fakepta_tpu.correlated_noises import add_common_correlated_noise
    from fakepta_tpu.fake_pta import Pulsar

    psrs = [Pulsar(np.linspace(0, 15 * const.yr, 780), 1e-7,
                   np.arccos(np.cos(0.07 * k * np.pi)), 0.41 * k % (2 * np.pi),
                   seed=k) for k in range(45)]
    t = _timeit(lambda: add_common_correlated_noise(
        psrs, orf="hd", log10_A=np.log10(2e-15), gamma=13 / 3, seed=3))
    return {"config": 3, "metric": "HD GWB array injections/s (45 psr)",
            "value": round(1 / t, 2), "unit": "inj/s"}


def config4():
    """100-psr GWB + DM noise + BayesEphem Roemer perturbation (ref +
    fake_pta.py:283-306, ephemeris.py:118-144)."""
    from fakepta_tpu import constants as const
    from fakepta_tpu.correlated_noises import (add_common_correlated_noise,
                                               add_roemer_delay)
    from fakepta_tpu.ephemeris import Ephemeris
    from fakepta_tpu.fake_pta import Pulsar, add_noise_array

    ephem = Ephemeris()
    psrs = [Pulsar(np.linspace(0, 15 * const.yr, 780), 1e-7,
                   np.arccos(1 - 2 * ((k + 0.5) / 100)), 2.39996 * k % (2 * np.pi),
                   seed=k, ephem=ephem) for k in range(100)]

    def full():
        add_noise_array(psrs, signal="dm_gp", spectrum="powerlaw",
                        log10_A=-13.8, gamma=3.0, seed=4)
        add_common_correlated_noise(psrs, orf="hd", log10_A=np.log10(2e-15),
                                    gamma=13 / 3, seed=5)
        jup = ephem.planets["jupiter"]["mass"]
        add_roemer_delay(psrs, "jupiter", d_mass=1e-4 * jup)
    t = _timeit(full, repeats=2)
    return {"config": 4, "metric": "full-array pipeline time (100 psr, GWB+DM+ephem)",
            "value": round(t, 3), "unit": "s"}


def config6():
    """Config 4 as ONE device program (VERDICT r2 #5): 100-psr GWB + DM +
    BayesEphem Roemer perturbation in the ensemble engine, Monte-Carlo over
    realizations — no per-pulsar host loop anywhere."""
    import jax

    from fakepta_tpu.batch import PulsarBatch
    from fakepta_tpu.parallel.mesh import make_mesh
    from fakepta_tpu.parallel.montecarlo import (EnsembleSimulator, GWBConfig,
                                                 RoemerConfig)

    n_dev = len(jax.devices())
    npsr, ntoa = 100, 780
    batch = PulsarBatch.synthetic(npsr=npsr, ntoa=ntoa, tspan_years=15.0,
                                  toaerr=1e-7, n_red=30, n_dm=100, seed=0)
    psd = _hd_psd(batch)
    toas_abs = _flagship_toas_abs(batch)
    sim = EnsembleSimulator(
        batch, gwb=GWBConfig(psd=psd, orf="hd"),
        include=("white", "dm", "gwb", "det"),
        roemer=RoemerConfig("jupiter", d_mass=1e-4 * 1.899e27),
        toas_abs=toas_abs, mesh=make_mesh(jax.devices()))
    nreal, chunk = _scaled(40_000, 4000)  # chunks pipeline; steady-state rate
    rate, obsf = _ensemble_rate(sim, nreal, chunk)
    return {"config": 6,
            "metric": "GWB+DM+BayesEphem realizations/s/chip (100 psr, one "
                      "device program)",
            "value": round(rate / n_dev, 2), "unit": "real/s/chip", **obsf}


def config7():
    """Full-noise ensemble: white + ECORR epoch blocks + per-backend system
    noise + red + DM on a replayed facade array (the samplers exist since r1
    but had never been in a measured number — VERDICT r2 weak #9)."""
    import jax

    from fakepta_tpu.batch import PulsarBatch
    from fakepta_tpu.fake_pta import Pulsar
    from fakepta_tpu.parallel.mesh import make_mesh
    from fakepta_tpu.parallel.montecarlo import EnsembleSimulator

    n_dev = len(jax.devices())
    day = 86400.0
    npsr, n_epochs, per_epoch = 40, 130, 4          # 130 epochs x 4 TOAs x 2 backends = 1040 TOAs/psr
    toas = np.concatenate([k * 30 * day + np.arange(per_epoch) * 600.0
                           for k in range(n_epochs)])
    psrs = []
    for k in range(npsr):
        p = Pulsar(toas, 1e-7, np.arccos(1 - 2 * (k + 0.5) / npsr),
                   2.39996 * k % (2 * np.pi), seed=k,
                   backends=["A.1400", "B.600"],
                   custom_model={"RN": 30, "DM": 100, "Sv": None})
        for backend in p.backends:
            p.noisedict[f"{p.name}_{backend}_log10_ecorr"] = -6.5
        p.add_red_noise(spectrum="powerlaw", log10_A=-14.0, gamma=13 / 3,
                        seed=k)
        p.add_dm_noise(spectrum="powerlaw", log10_A=-13.8, gamma=3.0, seed=k)
        p.add_system_noise(backend=str(p.backends[0]), components=20,
                           spectrum="powerlaw", log10_A=-13.5, gamma=2.5,
                           seed=k)
        psrs.append(p)
    batch = PulsarBatch.from_pulsars(psrs, n_red=30, n_dm=100, n_sys=20,
                                     ecorr=True)
    sim = EnsembleSimulator(batch, mesh=make_mesh(jax.devices()),
                            include=("white", "ecorr", "red", "dm", "sys"))
    nreal, chunk = _scaled(40_000, 4000)  # chunks pipeline; steady-state rate
    rate, obsf = _ensemble_rate(sim, nreal, chunk)
    return {"config": 7,
            "metric": "full-noise realizations/s/chip (40 psr, ECORR + "
                      "2-backend system noise)",
            "value": round(rate / n_dev, 2), "unit": "real/s/chip", **obsf}


def config8():
    """Flagship + per-realization hyperparameter sampling (NoiseSampling):
    per-pulsar red (log10_A, gamma) and global GWB (log10_A, gamma) drawn
    fresh every realization on device — population marginalization the
    reference cannot express at all. Measures the sampling overhead vs
    config 5's fixed-PSD program."""
    import jax

    from fakepta_tpu.parallel.mesh import make_mesh
    from fakepta_tpu.parallel.montecarlo import (EnsembleSimulator, GWBConfig,
                                                 NoiseSampling)
    from fakepta_tpu.scenarios.registry import flagship_batch

    n_dev = len(jax.devices())
    batch = flagship_batch()
    psd = _hd_psd(batch)
    sim = EnsembleSimulator(
        batch, gwb=GWBConfig(psd=psd, orf="hd"), mesh=make_mesh(jax.devices()),
        noise_sample=[NoiseSampling("red", log10_A=(-17.0, -13.0),
                                    gamma=(1.0, 5.0)),
                      NoiseSampling("gwb", log10_A=(-15.0, -14.0),
                                    gamma=(13 / 3, 13 / 3))])
    nreal, chunk = _scaled(100_000, 10_000)
    rate, obsf = _ensemble_rate(sim, nreal, chunk)
    return {"config": 8,
            "metric": "hyperparameter-sampled realizations/s/chip (100 psr, "
                      "per-psr red + GWB draws)",
            "value": round(rate / n_dev, 2), "unit": "real/s/chip", **obsf}


def config9():
    """Flagship + per-realization CW-source sampling (CGWSampling): every
    realization draws a full circular-SMBHB source (sky, chirp mass,
    frequency, strain, phase, polarization) and evaluates the evolving
    waveform on device, on top of the HD GWB + white + red + DM program —
    the continuous-wave population workload the reference cannot express."""
    import jax

    from fakepta_tpu.batch import PulsarBatch
    from fakepta_tpu.parallel.mesh import make_mesh
    from fakepta_tpu.parallel.montecarlo import (CGWSampling,
                                                 EnsembleSimulator, GWBConfig)

    n_dev = len(jax.devices())
    npsr, ntoa = 100, 780
    batch = PulsarBatch.synthetic(npsr=npsr, ntoa=ntoa, tspan_years=15.0,
                                  toaerr=1e-7, n_red=30, n_dm=100, seed=0)
    psd = _hd_psd(batch)
    toas_abs = _flagship_toas_abs(batch)
    sim = EnsembleSimulator(
        batch, gwb=GWBConfig(psd=psd, orf="hd"), mesh=make_mesh(jax.devices()),
        cgw_sample=CGWSampling(tref=float(toas_abs.mean())),
        toas_abs=toas_abs)
    nreal, chunk = _scaled(40_000, 4000)
    rate, obsf = _ensemble_rate(sim, nreal, chunk)
    return {"config": 9,
            "metric": "CW-population realizations/s/chip (100 psr, sampled "
                      "SMBHB source per realization)",
            "value": round(rate / n_dev, 2), "unit": "real/s/chip", **obsf}


def config10():
    """Scale-out: 256-pulsar HD GWB ensemble (VERDICT r4 #8). The regime where
    the (R, P, P) correlation tensor pressures HBM: with_corr=False keeps it a
    fusible intermediate, and the fused Pallas path's HBM-lean claim becomes
    testable. Reports the compiled chunk program's memory reservation."""
    import jax

    import dataclasses

    from fakepta_tpu.parallel.mesh import make_mesh
    from fakepta_tpu.parallel.montecarlo import EnsembleSimulator, GWBConfig
    from fakepta_tpu.scenarios import registry as scn_registry

    n_dev = len(jax.devices())
    # flagship spec scaled out to 256 psr — a derived variant, so the
    # batch stays pinned to the registered scenario's construction path
    scn256 = dataclasses.replace(scn_registry.get("flagship_100"), npsr=256)
    batch = scn256.batch_parts()[0]
    psd = _hd_psd(batch)
    sim = EnsembleSimulator(batch, gwb=GWBConfig(psd=psd, orf="hd"),
                            mesh=make_mesh(jax.devices()))
    nreal, chunk = _scaled(16_000, 2000)
    rate, obsf = _ensemble_rate(sim, nreal, chunk)
    row = {"config": 10,
           "metric": "scale-out realizations/s/chip (256 psr, HD GWB)",
           "value": round(rate / n_dev, 2), "unit": "real/s/chip", **obsf}
    # THIS program's static reservation (obs cost capture / memory_analysis),
    # not memory_stats()'s process-lifetime allocator peak — in a full sweep
    # the latter would report whatever earlier config peaked highest
    reserved = sim.last_report.cost.get("static_reservation_bytes")
    if reserved:
        row["peak_hbm_gb"] = round(reserved / 2**30, 2)
    return row


def config11():
    """Flagship + per-realization white-noise hyperparameter sampling
    (WhiteSampling): per-pulsar efac/log10_tnequad drawn fresh every
    realization on device, on top of the HD GWB + red + DM program. Measures
    the white-sampling overhead against config 5's fixed-sigma2 program."""
    import jax

    from fakepta_tpu.parallel.mesh import make_mesh
    from fakepta_tpu.parallel.montecarlo import (EnsembleSimulator, GWBConfig,
                                                 WhiteSampling)
    from fakepta_tpu.scenarios.registry import flagship_batch

    n_dev = len(jax.devices())
    batch = flagship_batch()
    psd = _hd_psd(batch)
    sim = EnsembleSimulator(
        batch, gwb=GWBConfig(psd=psd, orf="hd"), mesh=make_mesh(jax.devices()),
        white_sample=WhiteSampling(efac=(0.5, 2.5),
                                   log10_tnequad=(-8.0, -5.0)),
        # synthetic batch: sigma2 IS the raw toaerr^2 (explicit to skip the
        # provenance warning)
        toaerr2=np.asarray(batch.sigma2))
    nreal, chunk = _scaled(100_000, 10_000)
    rate, obsf = _ensemble_rate(sim, nreal, chunk)
    return {"config": 11,
            "metric": "white-sampled realizations/s/chip (100 psr, per-psr "
                      "efac/equad draws)",
            "value": round(rate / n_dev, 2), "unit": "real/s/chip", **obsf}


def config12():
    """Chaos lane (fakepta_tpu.faults, docs/RELIABILITY.md): the recovery
    overhead of the engine's transient-retry path. The same small ensemble
    run is timed clean and under a seeded FaultPlan injecting ONE transient
    dispatch fault per run (retried with zero backoff, so the figure is the
    pure re-dispatch cost, not sleep time); the recovered stream is
    asserted bit-identical to the clean run before the number ships.
    Under ``--scenario`` the ensemble is the registered scenario's own
    simulator (full noise menu, its GWB ORF) instead of the ad-hoc array —
    the same recovery contract, re-proven per scenario."""
    import jax

    from fakepta_tpu import faults
    from fakepta_tpu.batch import PulsarBatch
    from fakepta_tpu.parallel.mesh import make_mesh
    from fakepta_tpu.parallel.montecarlo import EnsembleSimulator, GWBConfig

    scn = _scenario()
    if scn is not None:
        sim = scn.build(mesh=make_mesh(jax.devices()))
        nreal, chunk = _scaled(512, 64)
    else:
        batch = PulsarBatch.synthetic(npsr=20, ntoa=260, tspan_years=15.0,
                                      toaerr=1e-7, n_red=10, n_dm=10, seed=0)
        sim = EnsembleSimulator(batch, gwb=GWBConfig(psd=_hd_psd(batch, 10),
                                                     orf="hd"),
                                mesh=make_mesh(jax.devices()))
        nreal, chunk = _scaled(2048, 256)
    policy = faults.RecoveryPolicy(backoff_s=0.0)

    def clean():
        return sim.run(nreal, seed=1, chunk=chunk, recovery=policy)

    def chaotic():
        # hit 0: fires even when nreal-scale collapses the run to 1 chunk
        plan = faults.FaultPlan(
            [faults.FaultSpec("mc.dispatch", "transient", at=(0,))])
        with faults.inject(plan):
            return sim.run(nreal, seed=1, chunk=chunk, recovery=policy)

    t_clean = _timeit(clean)
    t_chaos = _timeit(chaotic)
    out, base = chaotic(), clean()
    if not np.array_equal(out["curves"], base["curves"]):
        raise RuntimeError("recovered stream differs from the clean run — "
                           "the retry path is broken, refusing to record "
                           "an overhead figure for it")
    overhead = round(max(t_chaos / t_clean - 1.0, 0.0), 4)
    return {"config": 12,
            "metric": "transient-retry recovery overhead (1 fault/run)",
            "value": overhead, "unit": "frac",
            "fault_recovery_overhead_frac": overhead,
            "faults_recovered": int(
                out["report"].counters.get("faults.retries", 0))}


def config13():
    """Fleet lane (docs/SERVING.md "Fleet"): 3 subprocess ServePool
    replicas behind the spec-hash router, measured by the loadgen's
    multi-replica mode against ONE pool serving the same traffic. The
    workload cycles a spec working set LARGER than one pool's LRU warm
    capacity (the sharding win a single chip can demonstrate; multi-chip
    hosts add dispatcher parallelism on top), kills one replica at half
    load (failover A/B: ``fleet_lost_requests`` must be 0 and every
    failed-over response is bit-verified against its solo run), and all
    replicas share one persistent compile cache so cold starts are cache
    loads. The headline ``value`` is ``fleet_speedup_x``."""
    import tempfile

    import jax

    from fakepta_tpu.serve import ArraySpec, run_loadgen

    if jax.devices()[0].platform != "cpu":
        fleet_spec = ArraySpec(npsr=40, ntoa=260, n_red=10, n_dm=10,
                               gwb_ncomp=10)
        fleet_requests = 96
    else:
        fleet_spec = ArraySpec(npsr=8, ntoa=64, n_red=4, n_dm=4,
                               gwb_ncomp=4)
        fleet_requests = 72
    cache = tempfile.mkdtemp(prefix="fleet_cache_")
    row = run_loadgen(
        spec=fleet_spec, fleet=3, fleet_transport="process",
        n_requests=fleet_requests, sizes=(1, 2, 4), n_specs=6, seed=5,
        baseline=True, verify=3, kill_one_at=0.5,
        compile_cache_dir=cache)
    return {"config": 13,
            "metric": "fleet speedup vs one ServePool (3 replicas, "
                      "6-spec working set, 1 replica killed mid-load)",
            "value": row.get("fleet_speedup_x", 0.0), "unit": "x", **row}


def config14():
    """Streaming lane (fakepta_tpu.stream, docs/STREAMING.md): the
    incremental-append-vs-full-restage A/B. A stream accumulates bulk
    history on its frozen grids, then one new observing epoch arrives:
    ``append_speedup_x`` is the full-restage wall time over the additive
    rank-k append's (same kernels, same store — pure O(new-epoch) vs
    O(history) work; acceptance >= 5x at the flagship config), and
    ``stream_recompiles`` must stay 0 (every append rides an
    already-compiled (block bucket, epoch capacity) executable). The
    accelerator lane streams the flagship 100-psr x 15-yr array with
    ECORR epoch blocks; the CPU stand-in a reduced one (``platform``
    disambiguates, as everywhere)."""
    import jax

    from fakepta_tpu.stream.bench import run_append_ab

    yr_s = 365.25 * 86400.0
    if jax.devices()[0].platform != "cpu":
        row = run_append_ab(npsr=100, ntoa=780, tspan_years=15.0,
                            n_red=30, n_dm=100, nbin=10, history=780,
                            epoch_width=8, ecorr_dt=15.0 * yr_s / 64,
                            mesh=None, seed=0)
    else:
        row = run_append_ab(npsr=16, ntoa=128, tspan_years=15.0,
                            n_red=8, n_dm=8, nbin=8, history=1024,
                            epoch_width=8, ecorr_dt=15.0 * yr_s / 50,
                            mesh=None, seed=0)
    if row["stream_recompiles"]:
        raise RuntimeError("stream appends recompiled within their "
                           "buckets — the ladder canary is broken, "
                           "refusing to record a speedup through it")
    return {"config": 14,
            "metric": "single-epoch append speedup vs full restage "
                      "(streaming ingestion, ECORR epoch blocks)",
            "value": row["append_speedup_x"], "unit": "x", **row}


def config15():
    """Elastic chaos lane (docs/RELIABILITY.md "Fleet lifecycle"): the
    lifecycle A/B the health plane + elastic membership + autoscaler must
    survive in one run. The elastic loadgen ramps the config13 working
    set, WEDGES one replica's heartbeats at 20% (the breaker must drain
    it with zero client-visible timeouts — the wedge is caught out of
    band), SIGKILLs another at 45% (reader-EOF failover), and autoscales
    a fresh replica in at 70% (its shard prewarmed from the shared
    compile cache: ``fleet_join_steady_compiles`` must stay 0). Every
    failed-over response is bit-verified against a solo run before the
    row ships; ``fleet_lost_requests``/``fleet_timeouts`` must be 0. The
    headline ``value`` is ``fleet_p99_ms`` UNDER the chaos — the latency
    a client actually sees while the fleet loses, wedges and grows
    replicas. Under ``--scenario`` the fleet serves the registered
    scenario's spec (``Scenario.serve_spec()``) instead of the ad-hoc
    array — same lifecycle contract, re-proven per scenario."""
    import os
    import tempfile

    import jax

    from fakepta_tpu.serve import ArraySpec, run_elastic_loadgen
    from fakepta_tpu.serve.loadgen import measure_telemetry_overhead

    scn = _scenario()
    if scn is not None:
        spec = scn.serve_spec()
        n_requests, transport = (96, "process") \
            if jax.devices()[0].platform != "cpu" else (48, "inproc")
    elif jax.devices()[0].platform != "cpu":
        spec = ArraySpec(npsr=40, ntoa=260, n_red=10, n_dm=10,
                         gwb_ncomp=10)
        n_requests, transport = 96, "process"
    else:
        spec = ArraySpec(npsr=8, ntoa=64, n_red=4, n_dm=4, gwb_ncomp=4)
        n_requests, transport = 48, "inproc"
    cache = tempfile.mkdtemp(prefix="elastic_cache_")
    trace_path = os.path.join(cache, "elastic_trace.json")
    row = run_elastic_loadgen(
        spec=spec, n_replicas=3, transport=transport,
        n_requests=n_requests, sizes=(1, 2, 4), n_specs=6, seed=7,
        verify=3, compile_cache_dir=cache, trace_path=trace_path)
    if row["fleet_lost_requests"] or row["fleet_timeouts"]:
        raise RuntimeError(
            "the elastic chaos run lost requests or timed clients out — "
            "the lifecycle plane is broken, refusing to record its row")
    if transport == "inproc" and not row.get("trace_flows"):
        # with local replicas every request's router + replica + engine
        # spans share a trace_id; zero flow links means propagation broke
        raise RuntimeError(
            "the chaos run's Chrome trace has no trace-id flow links — "
            "trace propagation is broken, refusing to record its row")
    row.update(measure_telemetry_overhead(
        spec=spec, compile_cache_dir=cache))
    if not row.get("fleet_joins"):
        raise RuntimeError(
            "the autoscaler never joined a replica — the scale-up path "
            "is broken, refusing to record its row")
    if row.get("fleet_join_steady_compiles"):
        raise RuntimeError(
            "the autoscale-joined replica compiled in steady state — the "
            "shared-cache warm join is broken, refusing to record its row")
    if row.get("fleet_wedge_state") not in ("suspect", "wedged"):
        raise RuntimeError(
            "the wedged replica was never breakered — the health plane "
            "missed it, refusing to record its row")
    return {"config": 15,
            "metric": "client p99 under elastic chaos (wedge + kill + "
                      "autoscale-join, zero lost/timed-out)",
            "value": row.get("fleet_p99_ms", 0.0), "unit": "ms", **row}


def config16():
    """Multi-tenant gateway lane (fakepta_tpu.gateway, docs/GATEWAY.md):
    a Zipfian hot-spec tenant mix against a gateway-fronted fleet. The
    loadgen gives each tenant its own token and a skewed traffic split
    against a small in-flight budget, so the hot tenant runs into its
    weighted fair share (per-tenant 429s carrying ``retry_after_s`` — the
    isolation mechanism working); the Zipf identity pool makes repeats
    the common case, so the content-addressed store + single-flight fold
    carry most of the traffic (``gw_hit_rate``, acceptance >= 0.5, every
    store hit bit-verified against its own solo run before the row
    ships). A background appender streams TOA blocks through the gateway
    for the whole window and the stream is re-staged onto a 2x-Tspan
    template mid-load (the managed frozen-grid cutover): the loadgen
    refuses the row on any bit mismatch or dropped/duplicated append, and
    this config refuses it again on a cold cache or zero device-seconds
    saved. The headline ``value`` is ``gw_hit_rate``."""
    import jax

    from fakepta_tpu.serve import ArraySpec, run_gateway_loadgen

    if jax.devices()[0].platform != "cpu":
        spec = ArraySpec(npsr=40, ntoa=260, n_red=10, n_dm=10,
                         gwb_ncomp=10)
        n_requests, n_replicas = 96, 3
    else:
        spec = ArraySpec(npsr=8, ntoa=64, n_red=4, n_dm=4, gwb_ncomp=4)
        n_requests, n_replicas = 64, 2
    row = run_gateway_loadgen(
        spec=spec, n_tenants=3, n_requests=n_requests, sizes=(1, 2, 4),
        seed=11, n_specs=3, n_identities=12, n_replicas=n_replicas)
    if row["gw_hit_rate"] < 0.5:
        raise RuntimeError(
            f"gateway hit rate {row['gw_hit_rate']} < 0.5 at the scripted "
            f"Zipf skew — the result plane is cold, refusing to record "
            f"its row")
    if row["gw_device_s_saved"] <= 0.0:
        raise RuntimeError(
            "gateway cache hits saved zero device-seconds — the store "
            "never produced a hit, refusing to record its row")
    if not row["gw_verified"]:
        raise RuntimeError(
            "no gateway response was bit-verified — the hit-rate figure "
            "is unproven, refusing to record its row")
    if not row["gw_cutover_ms"]:
        raise RuntimeError(
            "the mid-load migration cutover never ran — refusing to "
            "record its row")
    return {"config": 16,
            "metric": "gateway cache hit rate under a Zipfian "
                      "multi-tenant mix (bit-verified, mid-load cutover)",
            "value": row["gw_hit_rate"], "unit": "fraction", **row}


def config17():
    """Scenario golden smoke (fakepta_tpu.scenarios, docs/SCENARIOS.md):
    the golden-run harness as a first-class suite config. Runs the
    ``--scenario`` selection (default ``ng15``) at smoke sizes and ships
    its full bench-schema row — the same row ``python -m
    fakepta_tpu.scenarios run`` emits, carrying ``scenario`` alongside
    ``platform`` so ``obs gate`` bands it on its own trajectory. The
    harness refuses the row itself on an append≡restage oracle divergence
    or a nonzero ``stream_recompiles``."""
    from fakepta_tpu.scenarios import golden

    name = _SCENARIO or "ng15"
    row = golden.golden_run(name, nreal=32, chunk=16, sample_steps=48,
                            sample_warmup=24, serve_requests=16,
                            max_append_blocks=8)
    return {"config": 17, **row}


def config18():
    """Factorized free-spectrum lane (fakepta_tpu.sample.factorized,
    docs/SAMPLING.md "Factorized free-spectrum"): the factorized-vs-joint
    sampling A/B plus the O(bins-touched) streaming refresh A/B.

    Part 1: a regular-grid (discrete-orthogonality) free-spectrum array is
    sampled jointly and as per-bin lanes over the SAME staged data. The
    f64 dense oracle must certify lnL additivity first and the measured
    factorized run must not recompile — the row is REFUSED otherwise,
    exactness and steady-state compile hygiene are not tradable for the
    speedup. The headline ``fs_speedup_x`` is ``fs_ess_per_s_per_chip``
    (critical-path lane wall — lanes are independent fleet sessions, one
    per replica) over the joint run's ``ess_per_s_per_chip``.

    Part 2: a :class:`~fakepta_tpu.stream.FactorizedRefresher` over a
    per-bin stream. Both refresh cycles follow an equal-width appended
    epoch (both pay the moment fold), but the incremental one carries a
    single bin's sinusoid on the stream's even cadence, so only that
    bin's lane re-samples: ``fs_refresh_speedup_x`` =
    ``fs_full_refresh_ms`` / ``fs_refresh_ms``, refused on any steady
    recompile. The accelerator lane runs flagship-shaped arrays; the CPU
    stand-in a reduced one (``platform`` disambiguates, as everywhere).
    """
    import dataclasses as _dc

    import jax

    from fakepta_tpu import constants as const
    from fakepta_tpu.batch import PulsarBatch
    from fakepta_tpu.infer import ComponentSpec, FreeParam, LikelihoodSpec
    from fakepta_tpu.sample import (FactorizedRun, SampleSpec, SamplingRun,
                                    factorized_oracle)
    from fakepta_tpu.stream import FactorizedRefresher, StreamState

    cpu = jax.devices()[0].platform == "cpu"
    if not cpu:
        npsr, ntoa, nb, lane_bins = 32, 384, 48, 4
        n_steps, warmup, segment = 192, 64, 32
        s_npsr, s_ntoa, s_nb, s_steps = 16, 96, 16, 96
    else:
        npsr, ntoa, nb, lane_bins = 4, 64, 8, 1
        n_steps, warmup, segment = 64, 16, 16
        s_npsr, s_ntoa, s_nb, s_steps = 3, 48, 16, 64

    def fs_model(nbin):
        return LikelihoodSpec(components=(
            ComponentSpec(target="red", spectrum="batch"),
            ComponentSpec(target="dm", spectrum="batch"),
            ComponentSpec(target="curn", nbin=nbin,
                          spectrum="free_spectrum",
                          free=(FreeParam("log10_rho", (-9.0, -5.0),
                                          per_bin=True),)),))

    # ---- part 1: factorized vs joint over identical staged data --------
    b = PulsarBatch.synthetic(npsr=npsr, ntoa=ntoa, tspan_years=10.0,
                              toaerr=1e-7, n_red=nb, n_dm=nb, seed=1)
    # exact discrete-orthogonality cadence t_k = k/T (no endpoint): the
    # grid on which the per-bin split is exact, which the oracle
    # certifies. Stored as HOST f64 (not the batch's device dtype) so the
    # f64 staging/oracle path reads the exact grid — a f32 round-trip of
    # the epochs alone costs ~1e-4 of additivity
    t = np.tile(np.arange(ntoa, dtype=np.float64)[None] / ntoa, (npsr, 1))
    b = _dc.replace(b, t_own=t, t_common=t)
    model = fs_model(nb)

    orc = factorized_oracle(b, model, lane_bins=lane_bins, data_seed=0,
                            n_probe=4)
    if orc["additivity_max_err"] > 1e-8 * max(orc["lnl_scale"], 1.0):
        raise RuntimeError(
            f"factorized lnL additivity defect "
            f"{orc['additivity_max_err']:.3e} exceeds the f64 oracle "
            f"tolerance — the per-bin split is NOT exact on this grid, "
            f"refusing to record a speedup through it")

    spec = SampleSpec(model=model, n_chains=4, warmup=warmup,
                      step_size=0.3, n_leapfrog=4)
    fr = FactorizedRun(b, spec, lane_bins=lane_bins, data_seed=0)
    fr.run(segment, seed=1, segment=segment)           # warm (compile)
    retr0 = fr.retraces
    res_f = fr.run(n_steps, seed=2, segment=segment)   # measured, warm
    if fr.retraces - retr0:
        raise RuntimeError(
            f"{fr.retraces - retr0} lane retraces in the measured "
            f"factorized run — the steady state is recompiling, refusing "
            f"to record a speedup through it")
    joint = SamplingRun(b, spec, residuals=fr.residuals)
    joint.run(segment, seed=1, segment=segment)        # warm (compile)
    res_j = joint.run(n_steps, seed=2, segment=segment)
    fs_ess = res_f["summary"]["fs_ess_per_s_per_chip"]
    j_ess = res_j["summary"]["ess_per_s_per_chip"]
    fs_speedup = fs_ess / max(j_ess, 1e-12)

    # ---- part 2: O(bins-touched) refresh vs full, equal appends --------
    tspan_s = 10.0 * const.yr
    template = PulsarBatch.synthetic(npsr=s_npsr, ntoa=s_ntoa,
                                     tspan_years=10.0, n_red=4, n_dm=4,
                                     seed=3)
    s_model = fs_model(s_nb)
    stream = StreamState(template, s_model)
    rng = np.random.default_rng(0)
    # every block is 40 wide: one shared (64-rung) bucket executable, and
    # 40 even-cadence samples resolve all s_nb harmonics alias-free
    # (width > 2*s_nb), so the sinusoid epoch's projection stays in its
    # own bin
    wide = 40
    t0 = np.sort(rng.uniform(0, 0.9 * tspan_s, (s_npsr, wide)), axis=1)
    stream.append(t0, rng.normal(0, 1e-7, (s_npsr, wide)),
                  sigma2=np.full((s_npsr, wide), 1e-14))
    s_spec = SampleSpec(model=s_model, n_chains=2, warmup=16,
                        n_leapfrog=3)
    ref = FactorizedRefresher(stream, s_spec, lane_bins=1, rhat_gate=1e9)
    ref.refresh(s_steps, seed=1, segment=segment)      # cold (compiles)

    def epoch(width, r):
        te = np.tile((np.arange(width) / width * tspan_s)[None],
                     (s_npsr, 1))
        return te, r(te), np.full((s_npsr, width), 1e-14)

    # incremental: the appended epoch excites ONE bin (f = 2/T sinusoid
    # on the even cadence), so one lane re-samples warm
    te, re_, s2 = epoch(wide, lambda te: 1e-6 * np.sin(
        2 * np.pi * (2.0 / tspan_s) * te))
    stream.append(te, re_, sigma2=s2)
    incr = ref.refresh(s_steps, seed=2, segment=segment)
    # full baseline: an equal-width epoch (white), every lane re-sampled
    # through the SAME code path — both cycles pay the moment fold
    te, re_, s2 = epoch(wide, lambda te: rng.normal(0, 1e-7, te.shape))
    stream.append(te, re_, sigma2=s2)
    full = ref.refresh(s_steps, seed=3, segment=segment, force_all=True)
    if incr["fs_recompiles"] or full["fs_recompiles"]:
        raise RuntimeError(
            "refresh lanes recompiled in the steady state — the "
            "O(bins-touched) claim is void, refusing to record it")
    refresh_speedup = full["fs_refresh_ms"] / max(incr["fs_refresh_ms"],
                                                  1e-9)

    return {"config": 18,
            "metric": "factorized free-spectrum lanes vs joint sampler "
                      "(per-chip ESS/s, f64-oracle-gated) + O(bins-"
                      "touched) streaming refresh",
            "value": round(fs_speedup, 2), "unit": "x",
            "fs_speedup_x": round(fs_speedup, 2),
            "fs_oracle_max_err": orc["additivity_max_err"],
            "fs_lane_count": res_f["summary"]["fs_lane_count"],
            "fs_ess_per_s_per_chip": fs_ess,
            "ess_per_s_per_chip": j_ess,
            "fs_wall_s_total": res_f["summary"]["fs_wall_s_total"],
            "fs_wall_s_critical": res_f["summary"]["fs_wall_s_critical"],
            "fs_recompiles": 0,
            "fs_lanes_touched": incr["fs_lanes_touched"],
            "fs_bins_touched": incr["fs_bins_touched"],
            "fs_refresh_ms": incr["fs_refresh_ms"],
            "fs_full_refresh_ms": full["fs_refresh_ms"],
            "fs_refresh_speedup_x": round(refresh_speedup, 2)}


def config5():
    """10k-realization MC of 100-psr HD GWB — the north-star (bench.py metric)."""
    import jax

    from fakepta_tpu.parallel.mesh import make_mesh
    from fakepta_tpu.parallel.montecarlo import EnsembleSimulator, GWBConfig
    from fakepta_tpu.scenarios.registry import flagship_batch

    n_dev = len(jax.devices())
    batch = flagship_batch()
    psd = _hd_psd(batch)
    sim = EnsembleSimulator(batch, gwb=GWBConfig(psd=psd, orf="hd"),
                            mesh=make_mesh(jax.devices()))
    # 10k-realization chunks pipeline on device with one packed host fetch at
    # the end; 100k total measures steady-state throughput (matches bench.py)
    nreal, chunk = _scaled(100_000, 10_000)
    rate, obsf = _ensemble_rate(sim, nreal, chunk)
    row = {"config": 5,
           "metric": "PTA realizations/sec/chip (100 psr, 15 yr, HD GWB)",
           "value": round(rate / n_dev, 2), "unit": "real/s/chip",
           "vs_baseline": round(rate / n_dev / (10_000 / (60.0 * 8)), 2),
           **obsf}

    # the detection lane (fakepta_tpu.detect): flagship + on-device optimal
    # statistic packed beside curves/autos — the configuration detection
    # studies run (no keep_corr, no (R, P, P) fetch). Rate and chunk bytes
    # come from that run's RunReport; `obs compare --fail-on-regression`
    # gates both (see bench.py's schema).
    nreal_os = min(nreal, 2 * chunk)
    sim.run(chunk, seed=98, chunk=chunk, os="hd")        # compile + warm up
    os_sum = sim.run(nreal_os, seed=1, chunk=chunk,
                     os="hd")["report"].summary()
    if os_sum.get("os_real_per_s_per_chip"):
        row["os_real_per_s_per_chip"] = os_sum["os_real_per_s_per_chip"]
    if os_sum.get("os_bytes_per_chunk"):
        row["os_bytes_per_chunk"] = os_sum["os_bytes_per_chunk"]

    # the inference lane (fakepta_tpu.infer): flagship + K=16 CURN
    # (log10_A, gamma) grid of GP-marginalized Woodbury lnL per realization
    # inside the chunk program — grid evaluations/s/chip and chunk bytes
    # from that run's RunReport (the bench.py line schema; reduced chunk
    # because the lane's per-realization moments are O(2M) per pulsar)
    from fakepta_tpu.infer import (ComponentSpec, FreeParam, InferSpec,
                                   LikelihoodSpec, theta_grid)
    lnl_model = LikelihoodSpec(components=(
        ComponentSpec(target="red", spectrum="batch"),
        ComponentSpec(target="dm", spectrum="batch"),
        ComponentSpec(target="curn", nbin=30, free=(
            FreeParam("log10_A", np.log10(2e-15) + np.array([-0.5, 0.5])),
            FreeParam("gamma", (3.0, 6.0)))),
    ))
    lnl_spec = InferSpec(model=lnl_model, theta=theta_grid(lnl_model, 4))
    chunk_lnl = max(n_dev, chunk // 5)
    sim.run(chunk_lnl, seed=97, chunk=chunk_lnl, lnlike=lnl_spec)  # warm up
    lnl_sum = sim.run(2 * chunk_lnl, seed=1, chunk=chunk_lnl,
                      lnlike=lnl_spec)["report"].summary()
    if lnl_sum.get("lnlike_evals_per_s_per_chip"):
        row["lnlike_evals_per_s_per_chip"] = \
            lnl_sum["lnlike_evals_per_s_per_chip"]
    if lnl_sum.get("lnlike_bytes_per_chunk"):
        row["lnlike_bytes_per_chunk"] = lnl_sum["lnlike_bytes_per_chunk"]

    # the sampling lane (fakepta_tpu.sample): on-device batched-MCMC CURN
    # free-spectrum posterior — ESS/s, chain-step throughput, worst-dim
    # R-hat and acceptance from the run summary (bench.py docstring
    # schema; flagship array on accelerator, reduced array on the CPU
    # stand-in where the host Laplace staging + per-step batched Cholesky
    # are intractable at 100 psr)
    from fakepta_tpu.sample import SampleSpec, SamplingRun
    if jax.devices()[0].platform != "cpu":
        s_batch, s_chains, s_steps, s_warm = batch, 256, 512, 256
    else:
        s_batch = PulsarBatch.synthetic(npsr=8, ntoa=96, tspan_years=15.0,
                                        toaerr=1e-7, n_red=8, n_dm=8, seed=0)
        s_chains, s_steps, s_warm = 16, 256, 128
    s_model = LikelihoodSpec(components=(
        ComponentSpec(target="red", spectrum="batch"),
        ComponentSpec(target="dm", spectrum="batch"),
        ComponentSpec(target="curn", nbin=6, spectrum="free_spectrum",
                      free=(FreeParam("log10_rho", (-9.0, -5.0),
                                      per_bin=True),)),
    ))
    s_spec = SampleSpec(model=s_model, n_chains=s_chains, n_temps=2,
                        step_size=0.35, n_leapfrog=10, thin=2,
                        warmup=s_warm)
    sampler = SamplingRun(s_batch, s_spec, mesh=make_mesh(jax.devices()),
                          data_seed=7)
    s_sum = sampler.run(s_steps, seed=7, segment=128,
                        pipeline_depth=2)["summary"]
    for key in ("ess_per_s_per_chip", "sample_steps_per_s_per_chip",
                "rhat_max", "accept_rate"):
        row[key] = s_sum[key]

    # the autotuner lane (fakepta_tpu.tune, docs/TUNING.md): search this
    # platform fingerprint's dispatch knobs (warm store => zero probes)
    # and A/B a tuned run against the hand-set measurement above — the
    # bench.py docstring documents the row schema, `obs gate` bands
    # tuned_speedup_x (higher-better) and tune_probe_s (lower-better)
    from fakepta_tpu import tune as tune_mod
    tuned_cfg, tune_info = tune_mod.search(
        batch, gwb=GWBConfig(psd=psd, orf="hd"), nreal_hint=nreal,
        max_candidates=8)
    row["tuned"] = 1
    row["tune_probe_s"] = round(float(tune_info["probe_s"]), 2)
    chunk_t = int(tuned_cfg.knobs.get("chunk", chunk))
    # warm the tuned-shape executable, then interleave hand-set and
    # tuned measurements best-of-2 (the bench.py A/B protocol: the
    # pipelined steady split would otherwise charge the tuned side its
    # compile, and a non-interleaved comparison folds host drift in)
    sim.run(chunk_t, seed=96, tuned=tuned_cfg)
    nreal_ab = min(nreal, 4 * max(chunk_t, chunk))
    hand_rate = tuned_rate = 0.0
    for _ in range(2):
        out_h = sim.run(nreal_ab, seed=1, chunk=chunk)
        hand_rate = max(hand_rate,
                        out_h["report"].steady_real_per_s_per_chip())
        out_t = sim.run(nreal_ab, seed=1, tuned=tuned_cfg)
        tuned_rate = max(tuned_rate,
                         out_t["report"].steady_real_per_s_per_chip())
    row["tuned_real_per_s_per_chip"] = round(tuned_rate, 2)
    if hand_rate > 0:
        row["tuned_speedup_x"] = round(tuned_rate / hand_rate, 3)

    # the serving lane (fakepta_tpu.serve, docs/SERVING.md): the built-in
    # load generator over a warm pool + microbatch coalescing scheduler —
    # request throughput, latency SLOs, coalescing stats and the speedup
    # over serial per-request run() dispatch (bench.py docstring schema;
    # responses bit-verified against solo runs inside the generator)
    from fakepta_tpu.scenarios import registry as scn_registry
    from fakepta_tpu.serve import ArraySpec, ServeConfig, run_loadgen
    if jax.devices()[0].platform != "cpu":
        serve_spec = scn_registry.get("flagship_100").serve_spec()
        serve_requests, serve_sizes = 128, (8, 16, 32, 64)
        serve_buckets = (64, 128, 256, 512)
    else:
        # CPU stand-in: many tiny requests over a small array (the
        # amortizable-fixed-cost regime; see bench.py)
        serve_spec = ArraySpec(npsr=16, ntoa=128, n_red=8, n_dm=8,
                               gwb_ncomp=8)
        serve_requests, serve_sizes = 128, (1, 2, 4)
        serve_buckets = (16, 128)
    serve_buckets = tuple(b for b in serve_buckets if b % n_dev == 0)
    serve_row = run_loadgen(
        spec=serve_spec, mesh=make_mesh(jax.devices()),
        n_requests=serve_requests, sizes=serve_sizes, kind="sim",
        baseline=True, verify=2, seed=5,
        config=ServeConfig(buckets=serve_buckets))
    for key in ("serve_qps_per_chip", "serve_p50_ms", "serve_p99_ms",
                "coalesce_factor", "pad_waste_frac", "serve_speedup_x",
                "serve_serial_qps_per_chip", "serve_retraces",
                "serve_steady_compiles"):
        if key in serve_row:
            row[key] = serve_row[key]

    # per-mode bytes/chunk (the whole-chunk megakernel + bf16-storage
    # mode, bench.py docstring schema): AOT cost capture only — the
    # roofline acceptance rides every suite round without a measured run
    # per mode
    from fakepta_tpu.parallel.montecarlo import EnsembleSimulator as _ES
    sim_mega = _ES(batch, gwb=GWBConfig(psd=psd, orf="hd"),
                   mesh=make_mesh(jax.devices()), use_pallas="mega")
    for name, cost in (("fused", sim_mega.chunk_cost(chunk)),
                       ("fused_bf16",
                        sim_mega.chunk_cost(chunk, precision="bf16"))):
        if cost.get("bytes_per_chunk"):
            row[f"cost_bytes_per_chunk_{name}"] = cost["bytes_per_chunk"]
        if cost.get("model_bytes_per_chunk"):
            row[f"model_bytes_per_chunk_{name}"] = \
                cost["model_bytes_per_chunk"]
    if row.get("model_bytes_per_chunk") and \
            row.get("model_bytes_per_chunk_fused"):
        row["fused_bytes_reduction_x"] = round(
            row["model_bytes_per_chunk"]
            / row["model_bytes_per_chunk_fused"], 2)

    # Peak device memory and an MFU estimate, both from the obs RunReport
    # (the memwatch watermark: sampled allocator stats max-aggregated over
    # local devices where the plugin provides them, else the
    # static-reservation + packed-buffer model; FLOPs from the one-time
    # cost-analysis capture).
    rep = sim.last_report
    peak = rep.memory.get("peak_hbm_bytes") \
        or rep.memory.get("peak_bytes_in_use") \
        or rep.cost.get("static_reservation_bytes")
    if peak:
        row["peak_hbm_gb"] = round(peak / 2**30, 2)
    flops = rep.cost.get("flops_per_chunk", 0.0) * (nreal / chunk)
    if flops > 0:
        achieved = flops * rate / nreal / n_dev
        row["achieved_tflops_per_chip"] = round(achieved / 1e12, 2)
        # v5e bf16 MXU peak ~197 TFLOP/s; this program is float32, so the
        # number is a conservative model-flops-utilization estimate
        row["mfu_vs_bf16_peak_pct"] = round(100 * achieved / 197e12, 2)
    return row


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--configs", type=int, nargs="*",
                    default=[1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13,
                             14, 15, 16, 17, 18])
    ap.add_argument("--platform", default=None)
    ap.add_argument("--scenario", default=None,
                    help="registered scenario name (fakepta_tpu.scenarios):"
                         " the chaos lanes (12, 15) rebuild their arrays "
                         "from it and the golden smoke (17) runs it; rows "
                         "carry a `scenario` column obs gate bands by")
    ap.add_argument("--update-baseline", action="store_true")
    ap.add_argument("--nreal-scale", type=float, default=1.0,
                    help="scale every ensemble config's realization count "
                         "(CPU stand-in runs use 0.1); rows are tagged")
    args = ap.parse_args()
    global _NREAL_SCALE, _SCENARIO
    _NREAL_SCALE = args.nreal_scale
    if args.scenario:
        from fakepta_tpu.scenarios import registry as scn_registry
        scn_registry.get(args.scenario)  # fail fast on a typo'd name
        _SCENARIO = args.scenario
    if args.platform:
        import jax
        jax.config.update("jax_platforms", args.platform)
    import jax

    # the dead-tunnel probe + CPU fallback bench.py already runs: suite rows
    # carry the same platform/fallback pair, so CPU stand-in rounds are
    # distinguishable from accelerator rounds across the whole trajectory
    # (previously suite.py silently dropped the fallback marker)
    from __graft_entry__ import _backend_reachable
    fallback = not _backend_reachable()
    if fallback:
        print("suite: accelerator backend unavailable; falling back to the "
              "CPU backend", file=sys.stderr)
        jax.config.update("jax_platforms", "cpu")

    fns = {1: config1, 2: config2, 3: config3, 4: config4, 5: config5,
           6: config6, 7: config7, 8: config8, 9: config9, 10: config10,
           11: config11, 12: config12, 13: config13, 14: config14,
           15: config15, 16: config16, 17: config17, 18: config18}
    rows = []
    ensemble_configs = {5, 6, 7, 8, 9, 10, 11, 12}  # the ones using _scaled
    # platform identity single-sourced through the tuner's fingerprint
    # (fakepta_tpu.tune) — the same probe `obs gate` uses for same-platform
    # row matching, so a suite row and the gate can never disagree about
    # which platform group a round belongs to
    from fakepta_tpu import tune as tune_mod
    platform = tune_mod.fingerprint().platform
    for c in args.configs:
        row = fns[c]()
        row["platform"] = platform
        if _SCENARIO and c in (12, 15, 17):
            # scenario-parameterized lanes: the row's obs identity includes
            # the scenario name (gate bands same-scenario same-platform)
            row.setdefault("scenario", _SCENARIO)
        if fallback:
            row["fallback"] = "accelerator backend unavailable; CPU stand-in"
        if _NREAL_SCALE != 1.0 and c in ensemble_configs:
            row["nreal_scale"] = _NREAL_SCALE
        print(json.dumps(row))
        rows.append(row)

    if args.update_baseline and rows:
        # rows are keyed by (platform, scenario): platform names the
        # section (same grouping `obs gate` bands by) and every row
        # carries its scenario — "-" for scenario-free configs — so a
        # scenario-parameterized round (configs 12/15/17 under
        # --scenario) never collides with the default round's entry in
        # the same table
        lines = [f"\n## Measured ({date.today().isoformat()}, "
                 f"{rows[0]['platform']}, {len(jax.devices())} device(s))\n\n",
                 "| # | scenario | metric | value | unit | notes |\n",
                 "|---|---|---|---|---|---|\n"]
        for r in rows:
            notes = []
            if "vs_baseline" in r:
                notes.append(f"{r['vs_baseline']}x target")
            if "peak_hbm_gb" in r:
                notes.append(f"peak HBM {r['peak_hbm_gb']} GB")
            if "achieved_tflops_per_chip" in r:
                notes.append(f"{r['achieved_tflops_per_chip']} TF/s/chip, "
                             f"~{r['mfu_vs_bf16_peak_pct']}% of bf16 peak")
            lines.append(f"| {r['config']} | {r.get('scenario', '-')} "
                         f"| {r['metric']} | {r['value']} "
                         f"| {r['unit']} | {', '.join(notes)} |\n")
        with open(REPO / "BASELINE.md", "a") as fh:
            fh.writelines(lines)
        print("appended to BASELINE.md")


if __name__ == "__main__":
    main()
