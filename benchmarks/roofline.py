"""Roofline + time-attribution for the flagship ensemble step (VERDICT r3 #2).

Three measurements on the live backend:

1. ``jax.profiler`` trace of steady-state chunks (load into TensorBoard or
   xprof to attribute time to the projection matmul vs the correlation
   contraction vs the draws);
2. XLA cost analysis of the compiled chunk program: FLOPs, bytes accessed,
   and the arithmetic intensity, placing the program on the v5e roofline
   (bf16 peak 197 TF/s, f32 ~half; HBM ~819 GB/s);
3. measured realizations/s/chip with the derived achieved-TF/s and
   achieved-GB/s, so the binding resource is explicit.

    python benchmarks/roofline.py                    # flagship config
    python benchmarks/roofline.py --npsr 100 --chunk 10000 --trace-dir /tmp/tr

Prints one JSON line per measurement. Cost/memory numbers are sourced from
the ``fakepta_tpu.obs`` RunReport each ``sim.run()`` attaches (one-time XLA
cost-analysis capture), plus compile time and the retrace-guard count — see
docs/OBSERVABILITY.md.
"""

import argparse
import json
import pathlib
import sys
import time

import numpy as np

REPO = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

V5E_BF16_PEAK = 197e12          # FLOP/s per chip
V5E_HBM_BW = 819e9              # bytes/s per chip


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--npsr", type=int, default=100)
    ap.add_argument("--ntoa", type=int, default=780)
    ap.add_argument("--chunk", type=int, default=10_000)
    ap.add_argument("--nreal", type=int, default=100_000)
    ap.add_argument("--trace-dir", default=None,
                    help="write a jax.profiler trace of 2 steady chunks here")
    ap.add_argument("--bases-bf16", action="store_true",
                    help="store the GP projection basis in bfloat16 (half "
                         "the projection HBM traffic; ~4e-3 operand rounding)")
    ap.add_argument("--stats-bf16", action="store_true",
                    help="cast residual blocks to bfloat16 at the statistic "
                         "boundary (halves the dominant (R,P,T) all_gather + "
                         "contraction traffic; ~4e-3 operand rounding)")
    ap.add_argument("--mode", choices=("xla", "fused", "mega"),
                    default="xla",
                    help="statistic path to measure: the two-stage XLA "
                         "einsums, the binned-correlation Pallas kernel, or "
                         "the whole-chunk megakernel (use_pallas='mega')")
    ap.add_argument("--precision", choices=("f32", "bf16"), default=None,
                    help="per-run statistic precision (run(precision=...)); "
                         "'bf16' + --mode mega is the bf16-storage mode")
    ap.add_argument("--platform", default=None)
    args = ap.parse_args()

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    from fakepta_tpu import spectrum as spectrum_lib
    from fakepta_tpu.batch import PulsarBatch
    from fakepta_tpu.parallel.mesh import make_mesh
    from fakepta_tpu.parallel.montecarlo import EnsembleSimulator, GWBConfig

    n_dev = len(jax.devices())
    batch = PulsarBatch.synthetic(npsr=args.npsr, ntoa=args.ntoa,
                                  tspan_years=15.0, toaerr=1e-7, n_red=30,
                                  n_dm=100, seed=0)
    f = np.arange(1, 31) / float(batch.tspan_common)
    psd = np.asarray(spectrum_lib.powerlaw(f, log10_A=np.log10(2e-15),
                                           gamma=13 / 3))
    use_pallas = {"xla": False, "fused": True, "mega": "mega"}[args.mode]
    sim = EnsembleSimulator(batch, gwb=GWBConfig(psd=psd, orf="hd"),
                            mesh=make_mesh(jax.devices()),
                            use_pallas=use_pallas,
                            bases_dtype="bf16" if args.bases_bf16 else "f32",
                            stats_dtype="bf16" if args.stats_bf16 else "f32")

    # compile + warm, then measure steady state
    warm = sim.run(args.chunk, seed=9, chunk=args.chunk,
                   precision=args.precision)
    t0 = time.perf_counter()
    out = sim.run(args.nreal, seed=1, chunk=args.chunk,
                  precision=args.precision)
    elapsed = time.perf_counter() - t0
    if not np.all(np.isfinite(out["curves"])):
        raise SystemExit("non-finite output")
    rate = args.nreal / elapsed / n_dev
    rep = out["report"]
    print(json.dumps({"measure": "throughput", "mode": args.mode,
                      "precision": rep.meta.get("precision", "f32"),
                      "real_per_s_per_chip": round(rate, 2),
                      "steady_real_per_s_per_chip": round(
                          rep.steady_real_per_s_per_chip(), 2),
                      "compile_s": round(warm["report"].compile_s, 3),
                      "retraces": rep.retraces,
                      "platform": jax.devices()[0].platform}))

    # XLA's cost model of one chunk program -> roofline placement, from the
    # obs RunReport's one-time capture (the 107.6 GB/chunk of BASELINE.md is
    # now a recorded artifact, not a hand computation)
    flops = rep.cost.get("flops_per_chunk", 0.0)
    bytes_acc = rep.cost.get("bytes_per_chunk", 0.0)
    if flops > 0:
        chunks = args.nreal / args.chunk
        achieved_flops = flops * chunks / elapsed / n_dev
        achieved_bw = bytes_acc * chunks / elapsed / n_dev
        intensity = flops / max(bytes_acc, 1.0)
        ridge = V5E_BF16_PEAK / V5E_HBM_BW      # FLOP/byte where roofline bends
        bound = "compute" if intensity > ridge else "memory"
        print(json.dumps({
            "measure": "roofline", "mode": args.mode,
            "program_flops_per_chunk": flops,
            "program_bytes_per_chunk": bytes_acc,
            "model_bytes_per_chunk": rep.cost.get("model_bytes_per_chunk"),
            # bench.py-schema spelling, diffable by `obs compare`
            "intensity_flop_per_byte": round(intensity, 2),
            "arithmetic_intensity_flop_per_byte": round(intensity, 2),
            "ridge_point_flop_per_byte": round(ridge, 2),
            "bound": bound,
            "achieved_tflops_per_chip": round(achieved_flops / 1e12, 2),
            "mfu_vs_bf16_peak_pct": round(
                100 * achieved_flops / V5E_BF16_PEAK, 2),
            "achieved_hbm_gb_per_s": round(achieved_bw / 1e9, 2),
            "hbm_utilization_pct": round(100 * achieved_bw / V5E_HBM_BW, 2),
        }))
    reserved = rep.cost.get("static_reservation_bytes")
    if reserved:
        print(json.dumps({"measure": "memory",
                          "static_reservation_gb":
                              round(reserved / 2**30, 2)}))

    # per-mode bytes/chunk (bench.py docstring schema): AOT cost capture of
    # the megakernel program at f32 and under the bf16-storage mode beside
    # this run's measured mode — the roofline acceptance as one JSON row
    # (measured bytes + the analytic HBM model; the model is the source of
    # truth on platforms whose cost analysis can't see TPU fusion —
    # fakepta_tpu.ops.megakernel.chunk_bytes_model)
    sim_mega = (sim if args.mode == "mega" else EnsembleSimulator(
        batch, gwb=GWBConfig(psd=psd, orf="hd"),
        mesh=make_mesh(jax.devices()), use_pallas="mega"))
    sim_xla = sim if args.mode == "xla" else EnsembleSimulator(
        batch, gwb=GWBConfig(psd=psd, orf="hd"),
        mesh=make_mesh(jax.devices()))
    per_mode = {"measure": "bytes_per_mode"}
    for name, cost in (("xla", sim_xla.chunk_cost(args.chunk)),
                       ("fused", sim_mega.chunk_cost(args.chunk)),
                       ("fused_bf16", sim_mega.chunk_cost(
                           args.chunk, precision="bf16"))):
        if cost.get("bytes_per_chunk"):
            per_mode[f"cost_bytes_per_chunk_{name}"] = \
                cost["bytes_per_chunk"]
        if cost.get("model_bytes_per_chunk"):
            per_mode[f"model_bytes_per_chunk_{name}"] = \
                cost["model_bytes_per_chunk"]
    if per_mode.get("model_bytes_per_chunk_xla") and \
            per_mode.get("model_bytes_per_chunk_fused"):
        per_mode["fused_bytes_reduction_x"] = round(
            per_mode["model_bytes_per_chunk_xla"]
            / per_mode["model_bytes_per_chunk_fused"], 2)
        per_mode["fused_bf16_bytes_reduction_x"] = round(
            per_mode["model_bytes_per_chunk_xla"]
            / per_mode["model_bytes_per_chunk_fused_bf16"], 2)
    print(json.dumps(per_mode))

    if args.trace_dir:
        with jax.profiler.trace(args.trace_dir):
            sim.run(2 * args.chunk, seed=2, chunk=args.chunk)
        print(json.dumps({"measure": "trace", "dir": args.trace_dir}))


if __name__ == "__main__":
    main()
